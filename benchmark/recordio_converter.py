"""Convert benchmark datasets to recordio chunk files.

Parity: reference benchmark/fluid/recordio_converter.py (mnist / cifar10 /
flowers -> recordio for the reader-op input path). Writes through
paddle_tpu.fluid.recordio_writer onto the C++ chunked record format
(csrc/recordio.cpp), which layers.open_recordio_file / the threaded
prefetcher consume.

Run:  python benchmark/recordio_converter.py --dataset mnist --out /tmp/m
"""
import argparse
import os


def _feeder(shapes, dtypes, lod_levels):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import framework, unique_name
    main, startup = fluid.Program(), fluid.Program()
    # scoped guards: the feed vars stay usable after exit (DataFeeder only
    # reads their shapes/dtypes), and the process-global default programs
    # are left untouched
    with unique_name.guard(), framework.program_guard(main, startup):
        feed_vars = [
            fluid.layers.data(name='f%d' % i, shape=list(shp), dtype=dt,
                              lod_level=ll)
            for i, (shp, dt, ll) in enumerate(
                zip(shapes, dtypes, lod_levels))
        ]
    return fluid.DataFeeder(feed_list=feed_vars, place=fluid.CPUPlace())


def convert_2_recordio(py_reader, outfilepath, batch_size, shape_data,
                       shape_label):
    import paddle_tpu as paddle
    from paddle_tpu.fluid import recordio_writer
    feeder = _feeder([shape_data, shape_label], ['float32', 'int64'], [0, 0])
    reader = paddle.batch(py_reader, batch_size=batch_size)
    return recordio_writer.convert_reader_to_recordio_file(
        outfilepath, reader, feeder)


def prepare_mnist(outpath, batch_size):
    import paddle_tpu.dataset.mnist as mnist
    outfilepath = os.path.join(outpath, 'mnist.recordio')
    return convert_2_recordio(mnist.train(), outfilepath, batch_size,
                              [784], [1])


def prepare_cifar10(outpath, batch_size):
    import paddle_tpu.dataset.cifar as cifar
    outfilepath = os.path.join(outpath, 'cifar.recordio')
    return convert_2_recordio(cifar.train10(), outfilepath, batch_size,
                              [3, 32, 32], [1])


def prepare_flowers(outpath, batch_size):
    import paddle_tpu.dataset.flowers as flowers
    outfilepath = os.path.join(outpath, 'flowers.recordio')
    return convert_2_recordio(flowers.train(), outfilepath, batch_size,
                              [3, 224, 224], [1])


def main():
    p = argparse.ArgumentParser('recordio converter (TPU).')
    p.add_argument('--dataset', choices=['mnist', 'cifar10', 'flowers'],
                   default='mnist')
    p.add_argument('--out', type=str, required=True)
    p.add_argument('--batch_size', type=int, default=32)
    args = p.parse_args()
    os.makedirs(args.out, exist_ok=True)
    n = {'mnist': prepare_mnist, 'cifar10': prepare_cifar10,
         'flowers': prepare_flowers}[args.dataset](args.out, args.batch_size)
    print('wrote %d batches' % n)


if __name__ == '__main__':
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), '..'))
    main()
