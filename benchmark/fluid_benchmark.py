"""Fluid benchmark driver CLI.

Parity: reference benchmark/fluid/fluid_benchmark.py + args.py — same model
set (mnist / resnet / vgg / machine_translation / stacked_dynamic_lstm) and
flag surface, retargeted at TPU:

- --device TPU replaces GPU; --chips (alias --gpus) > 1 runs the GSPMD
  data-parallel ParallelExecutor instead of the NCCL SSA-graph executor.
- --update_method pserver routes through DistributeTranspiler, whose
  output here is a mesh-annotated program (ICI/DCN collectives), not a
  gRPC pserver pair; nccl2 maps to the same collective path.
- --memory_optimize wires jax.checkpoint rematerialisation;
  --use_inference_transpiler folds BN for the test program;
  --profile wraps the timed passes in the fluid profiler (per-op table).

Run:  python benchmark/fluid_benchmark.py --model mnist --iterations 20
"""
import argparse
import time

import numpy as np

BENCHMARK_MODELS = [
    'machine_translation', 'resnet', 'vgg', 'mnist', 'stacked_dynamic_lstm',
    'transformer',   # TPU extension: the flagship fused-attention model,
                     # the one --sp (sequence parallelism) applies to
]


def parse_args(argv=None):
    p = argparse.ArgumentParser('Fluid model benchmarks (TPU).')
    p.add_argument('--model', type=str, choices=BENCHMARK_MODELS,
                   default='resnet')
    p.add_argument('--batch_size', type=int, default=32)
    p.add_argument('--learning_rate', type=float, default=0.001)
    p.add_argument('--skip_batch_num', type=int, default=5,
                   help='minibatches to skip before timing starts')
    p.add_argument('--iterations', type=int, default=80,
                   help='timed minibatches per pass (0 = whole reader)')
    p.add_argument('--pass_num', type=int, default=1)
    p.add_argument('--data_format', type=str, default='NCHW',
                   choices=['NCHW', 'NHWC'])
    p.add_argument('--device', type=str, default='TPU',
                   choices=['CPU', 'TPU', 'GPU'],
                   help='GPU is accepted for script compat and means TPU')
    p.add_argument('--chips', '--gpus', dest='chips', type=int, default=1,
                   help='>1 uses the GSPMD data-parallel ParallelExecutor')
    p.add_argument('--data_set', type=str, default='cifar10',
                   choices=['cifar10', 'flowers'])
    p.add_argument('--infer_only', action='store_true')
    p.add_argument('--no_test', action='store_true')
    p.add_argument('--memory_optimize', action='store_true')
    p.add_argument('--use_fake_data', action='store_true')
    p.add_argument('--profile', action='store_true')
    p.add_argument('--update_method', type=str, default='local',
                   choices=['local', 'pserver', 'nccl2'])
    p.add_argument('--no_random', action='store_true')
    p.add_argument('--use_inference_transpiler', action='store_true')
    p.add_argument('--tp', type=int, default=1,
                   help='tensor-parallel degree (TensorParallelTranspiler; '
                        'Megatron layouts over a tp mesh axis)')
    p.add_argument('--sp', type=int, default=1,
                   help='sequence-parallel degree (SequenceParallel'
                        'Transpiler; attention rides the ring — the model '
                        'must use fused_attention)')
    p.add_argument('--pp', type=int, default=1,
                   help='pipeline stages (transformer only: packs the '
                        'decoder layers into S device_guard stages, '
                        'PipelineTranspiler schedules them as GPipe)')
    p.add_argument('--n_micro', type=int, default=2,
                   help='pipeline microbatches per step (with --pp)')
    return p.parse_args(argv)


def _build(args):
    """Build the chosen model in fresh programs; normalize the per-model
    get_model() return tuples to (loss, infer_prog, train_r, test_r, acc)."""
    from paddle_tpu.models import (machine_translation, mnist, resnet,
                                   stacked_dynamic_lstm, vgg)
    import paddle_tpu.fluid as fluid

    if args.model == 'mnist':
        loss, infer, train_r, test_r, acc = mnist.get_model(
            args.batch_size, args.learning_rate)
    elif args.model == 'resnet':
        loss, acc, train_r, test_r = resnet.get_model(
            args.data_set, batch_size=args.batch_size,
            learning_rate=args.learning_rate)
        infer = None
    elif args.model == 'vgg':
        loss, infer, train_r, test_r, acc = vgg.get_model(
            args.data_set, args.batch_size, args.learning_rate)
    elif args.model == 'machine_translation':
        loss, infer, train_r, test_r, feeding = machine_translation.get_model(
            batch_size=args.batch_size)
        acc = None
    elif args.model == 'transformer':
        from paddle_tpu.models import transformer
        loss, tok, train_r, test_r, feeds = transformer.get_model(
            batch_size=args.batch_size,
            pp_decoder=args.pp if args.pp > 1 else False)
        infer, acc = None, None
    else:
        loss, infer, train_r, test_r, acc = stacked_dynamic_lstm.get_model(
            batch_size=args.batch_size)
    return loss, infer, train_r, test_r, acc


def _feed_vars(program):
    """Data vars in declaration order (layers.data marks is_data)."""
    return [v for v in program.global_block().vars.values()
            if getattr(v, 'is_data', False)]


def _fake_batch(feed_vars, batch_size):
    """Synthesize one batch (reference --use_fake_data semantics: no real
    dataset read). Only for lod-0 models — sequence models need real token
    structure, so they fall back to caching one real batch."""
    if any(v.lod_level > 0 for v in feed_vars):
        return None
    samples = []
    rng = np.random.RandomState(0)
    for _ in range(batch_size):
        row = []
        for v in feed_vars:
            shape = [int(s) for s in v.shape[1:]]
            if 'int' in str(v.dtype):
                # ones, not zeros: id 0 is the pad token in the seq models,
                # and an all-pad batch has zero loss weight (NaN loss)
                row.append(np.ones(shape or [1], dtype='int64'))
            else:
                row.append(rng.rand(*shape).astype('float32'))
        samples.append(tuple(row))
    return samples


def run_benchmark(args):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import profiler

    main = fluid.Program()
    startup = fluid.Program()
    if args.no_random:
        main.random_seed = startup.random_seed = 90
    from paddle_tpu.fluid import framework, unique_name
    with unique_name.guard(), framework.program_guard(main, startup):
        loss, infer_prog, train_reader, test_reader, acc = _build(args)

        if args.update_method in ('pserver', 'nccl2'):
            t = fluid.DistributeTranspiler()
            t.transpile(trainer_id=0, program=main, trainers=args.chips,
                        startup_program=startup)
            main = t.get_trainer_program()
        if (args.tp > 1 or args.sp > 1 or args.pp > 1) and args.chips > 1 \
                and args.update_method == 'local':
            raise ValueError(
                '--tp/--sp/--pp with --chips > 1: use --update_method '
                'pserver (DistributeTranspiler dp composes with tp/sp/pp '
                'through the Executor; the local ParallelExecutor builds '
                'its own dp-only mesh)')
        if args.pp > 1 and args.model != 'transformer':
            raise ValueError('--pp: only the transformer model builds '
                             'device_guard pipeline stages')
        if args.pp > 1:
            fluid.PipelineTranspiler(n_micro=args.n_micro).transpile(main)
        for prog in [main] + ([infer_prog] if infer_prog is not None
                              else []):
            if args.tp > 1:
                fluid.TensorParallelTranspiler(tp=args.tp).transpile(prog)
            if args.sp > 1:
                fluid.SequenceParallelTranspiler(sp=args.sp).transpile(prog)
        if args.memory_optimize:
            fluid.memory_optimize(main)
        if args.infer_only and infer_prog is None:
            raise ValueError(
                "--infer_only: model %r builds no inference program; "
                "pick one of mnist/vgg/machine_translation/"
                "stacked_dynamic_lstm" % args.model)

        place = (fluid.CPUPlace() if args.device == 'CPU'
                 else fluid.TPUPlace(0))
        exe = fluid.Executor(place)
        exe.run(startup)

        if args.use_inference_transpiler and infer_prog is not None:
            # after startup: the fold needs initialized weights in scope
            fluid.InferenceTranspiler().transpile(infer_prog, place)

        fvars = _feed_vars(main)
        feeder = fluid.DataFeeder(feed_list=fvars, place=place)

        pe = None
        if args.chips > 1 and args.update_method == 'local' \
                and not args.infer_only:
            pe = fluid.ParallelExecutor(main_program=main,
                                        loss_name=loss.name,
                                        num_devices=args.chips)

        fetch = [loss.name] + ([acc.name] if acc is not None else [])
        batches = None
        if args.use_fake_data:
            fake = _fake_batch(fvars, args.batch_size)
            batches = [fake if fake is not None
                       else next(iter(train_reader()))]

        total_ex, total_s, outs = 0, 0.0, None
        for pass_id in range(args.pass_num):
            it, t0 = 0, None
            # iterations=0 means 'whole reader'; for fake data that is
            # unbounded, so run a sustained 100-timed-batch pass
            fake_iters = args.skip_batch_num + (args.iterations or 100)
            reader = (iter(batches * max(1, fake_iters))
                      if batches else train_reader())
            if args.profile and pass_id == 0:
                profiler.start_profiler('All', op_detail=True)
            for data in reader:
                if args.iterations and it >= args.skip_batch_num + \
                        args.iterations:
                    break
                if it == args.skip_batch_num:
                    t0 = time.time()
                feedd = feeder.feed(data)
                if pe is not None:
                    outs = pe.run(fetch, feed=feedd)
                elif args.infer_only and infer_prog is not None:
                    outs = exe.run(infer_prog, feed=feedd, fetch_list=fetch)
                else:
                    outs = exe.run(main, feed=feedd, fetch_list=fetch)
                it += 1
                if t0 is not None:
                    total_ex += len(data)
            if args.profile and pass_id == 0:
                profiler.stop_profiler('total',
                                       '/tmp/fluid_benchmark.profile')
            dt = time.time() - (t0 or time.time())
            total_s += dt
            if outs is None:
                raise RuntimeError(
                    'no batches ran: the train reader yielded nothing '
                    '(dataset smaller than one batch?) or pass_num is 0')
            lv = float(np.asarray(outs[0]).mean())
            msg = 'Pass: %d, Loss: %f' % (pass_id, lv)
            if acc is not None and not args.no_test and test_reader and \
                    infer_prog is not None:
                accs = []
                for td in test_reader():
                    a = exe.run(infer_prog, feed=feeder.feed(td),
                                fetch_list=[acc.name])
                    accs.append(float(np.asarray(a[0]).mean()))
                msg += ', Test Accuracy: %f' % float(np.mean(accs))
            print(msg)
        if total_s > 0:
            print('Avg throughput: %.2f examples/sec'
                  % (total_ex / total_s))
        if outs is None:
            raise RuntimeError('no batches ran (pass_num=0?)')
        return float(np.asarray(outs[0]).mean())


if __name__ == '__main__':
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), '..'))
    a = parse_args()
    if a.device == 'CPU':
        import jax
        jax.config.update('jax_platforms', 'cpu')
    run_benchmark(a)
