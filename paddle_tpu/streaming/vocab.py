"""Dynamic vocab: the host-side id->row indirection in front of a table.

The reference's pserver era served recommenders whose id space DRIFTS —
new users/items appear mid-stream, old ones go cold — by letting the
parameter server grow its table. A compiled TPU step cannot grow
anything: the table is a fixed [capacity, D] persistable whose shape is
baked into every cached executable. :class:`VocabTable` closes the gap
entirely on the host, BEFORE the feed: raw (unbounded, arbitrary int)
ids translate to rows of the fixed table, so the compiled step signature
never changes as the vocab drifts (docs/embedding.md "streaming ids").

  * ADMISSION by frequency: an id below `admit_count` sightings maps to
    the shared COLD ROW (row 0 by default) — it still trains (against
    the shared row), but never steals a private row from the hot set.
    Crossing the threshold claims a free row, or evicts the
    least-recently-used cold resident.
  * EVICTION is safe because the sparse update path touches only the
    rows in the batch (docs/embedding.md): a row no batch references is
    dead weight on the device. Rows referenced by an IN-FLIGHT batch
    are pinned (`translate` returns a :class:`Lease`; release it after
    the step) — a pinned row is never chosen for eviction, and an
    explicit `evict()` of one fails with the typed :class:`RowPinned`
    instead of tearing the update the step is about to scatter.
  * An evicted row's table row AND optimizer moments are stale garbage
    for its next owner; `drain_resets()` hands the trainer the rows to
    zero and :class:`RowResetter` applies the zeroing as ONE fixed-shape
    jitted scatter (padded with an out-of-range index, mode='drop'), so
    steady-state training still performs zero online compiles.

The refcount+recency bookkeeping is `utils.lru.RefCountedLRU`, shared
with the serving tier's PrefixCache. The table serializes to a JSON-able
`state_dict()` which the Trainer folds into checkpoint meta, so
exact-step resume holds under vocab drift (docs/robustness.md#elastic).

Thread-safe: `translate` runs on the reader-prefetch worker while the
consumer releases leases and drains resets — one lock covers the map.
"""
import collections
import threading

import numpy as np

from .. import obs
from ..utils.lru import RefCountedLRU

__all__ = ['VocabTable', 'RowPinned', 'VocabFull', 'Lease',
           'table_state_names', 'RowResetter']

_C_ADMITTED = obs.counter('streaming.rows_admitted')
_C_EVICTED = obs.counter('streaming.rows_evicted')


class RowPinned(RuntimeError):
    """evict() targeted a row some in-flight batch still references —
    evicting it would zero a row whose gradient is about to land (a
    torn update). Release the lease first."""


class VocabFull(RuntimeError):
    """An id crossed the admission threshold but the table has no free
    row, nothing is evictable (everything pinned), and the table was
    built without a cold row to fall back on."""


class Lease(object):
    """Pin on the rows one translated batch references. Hold it while
    the batch's step is in flight; `release()` (idempotent) un-pins.
    The rows stay resident — release only makes them evictable again."""

    __slots__ = ('_vocab', '_ids', '_released')

    def __init__(self, vocab, ids):
        self._vocab = vocab
        self._ids = ids
        self._released = False

    def release(self):
        if self._released:
            return
        self._released = True
        self._vocab._release(self._ids)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False


class VocabTable(object):
    """Host-side raw-id -> row map over a fixed [capacity, D] table.

    capacity:     TOTAL rows of the device table this map fronts
                  (including the cold row).
    table:        name of the table persistable (and, through
                  `table_state_names`, its optimizer moments) — what the
                  trainer zeroes on eviction and the publisher pushes.
    admit_count:  sightings before an id earns a private row. 1 admits
                  on first sight.
    cold_row:     the shared row un-admitted ids train against (default
                  0). None reserves no cold row — then admission
                  pressure with nothing evictable raises VocabFull
                  instead of deferring.
    max_pending:  bound on the not-yet-admitted frequency map (the id
                  universe is unbounded; the counts must not be). On
                  overflow the OLDEST pending count is dropped — an id
                  that went cold before admission restarts its count.
    """

    def __init__(self, capacity, table=None, admit_count=1, cold_row=0,
                 max_pending=None, name=None):
        self.capacity = int(capacity)
        self.table = table
        self.name = name or table or 'vocab'
        self.admit_count = int(admit_count)
        if self.admit_count < 1:
            raise ValueError('admit_count must be >= 1, got %r'
                             % (admit_count,))
        self.cold_row = None if cold_row is None else int(cold_row)
        reserved = 0 if self.cold_row is None else 1
        if self.capacity <= reserved:
            raise ValueError('capacity %d leaves no assignable row past '
                             'the cold row' % self.capacity)
        if self.cold_row is not None and not (
                0 <= self.cold_row < self.capacity):
            raise ValueError('cold_row %d outside [0, %d)'
                             % (self.cold_row, self.capacity))
        self.max_pending = int(max_pending) if max_pending is not None \
            else max(1024, 8 * self.capacity)
        self._lock = threading.Lock()
        self._map = RefCountedLRU()      # raw id -> row
        self._free = [r for r in range(self.capacity - 1, -1, -1)
                      if r != self.cold_row]          # pop() -> low rows first
        self._pending = {}               # raw id -> sighting count
        # FIFO of pending ids (deque: the overflow pop is O(1) under
        # the translate lock — a list's pop(0) would shift max_pending
        # elements per new id once the bound is hit)
        self._pending_order = collections.deque()
        self._resets = []                # evicted rows awaiting zeroing
        # admission/eviction MOVE log for the tier store
        # (embedding.tiers.TieredVocabTable): disabled by default so a
        # plain table never accumulates an undrained list
        self._log_moves = False
        self._moves = []                 # [('admit'|'evict', raw, row)]
        # cumulative stats (the obs counters carry process-wide twins)
        self.rows_admitted = 0
        self.rows_evicted = 0
        self.deferred = 0                # admissions deferred to cold row
        self.cold_hits = 0               # translations routed to cold row
        self.translations = 0

    # -- translation -------------------------------------------------------

    def translate(self, ids, pin=True):
        """Map raw ids (any int array shape) to rows of the fixed table,
        admitting/evicting as the stream demands. Returns (rows, lease):
        rows an int64 array of ids' shape, lease pinning every private
        row the batch references (None when pin=False). Release the
        lease once the step that consumes this batch has completed."""
        arr = np.asarray(ids)
        flat = arr.reshape(-1)
        uniq, inverse, counts = np.unique(flat, return_inverse=True,
                                          return_counts=True)
        urows = np.empty(uniq.shape, np.int64)
        admitted, evicted, pinned = [], [], []
        with self._lock:
            self.translations += 1
            for i, raw in enumerate(uniq):
                raw = int(raw)
                row = self._map.get(raw)
                if row is None:
                    # every OCCURRENCE is a sighting (a batch with the
                    # same id 5 times is 5 votes for admission)
                    row = self._maybe_admit_locked(
                        raw, admitted, evicted, sightings=int(counts[i]))
                else:
                    self._map.touch(raw)
                if row is None:          # below threshold / deferred
                    if self.cold_row is None:
                        raise VocabFull(
                            'vocab %r: id %d needs a row but the table '
                            'is full, nothing is evictable, and no cold '
                            'row was reserved' % (self.name, raw))
                    self.cold_hits += 1
                    urows[i] = self.cold_row
                    continue
                urows[i] = row
                if pin:
                    self._map.ref(raw)
                    pinned.append(raw)
            resident = len(self._map)
        out = urows[inverse]
        if admitted:
            _C_ADMITTED.inc(len(admitted))
            obs.event('streaming.admit', vocab=self.name,
                      rows=len(admitted), sample=admitted[:8],
                      resident=resident)
        if evicted:
            _C_EVICTED.inc(len(evicted))
            obs.event('streaming.evict', vocab=self.name,
                      rows=len(evicted), sample=evicted[:8],
                      resident=resident)
        lease = Lease(self, pinned) if pin else None
        return out.reshape(arr.shape), lease

    def lookup(self, ids):
        """Read-only translation for the SERVING side: resident ids map
        to their rows, everything else to the cold row (or raises when
        no cold row exists). No admission, no counting, no pinning."""
        arr = np.asarray(ids)
        flat = arr.reshape(-1)
        out = np.empty(flat.shape, np.int64)
        with self._lock:
            for i, raw in enumerate(flat):
                row = self._map.get(int(raw))
                if row is None:
                    if self.cold_row is None:
                        raise KeyError('id %d is not resident in vocab %r'
                                       % (int(raw), self.name))
                    row = self.cold_row
                out[i] = row
        return out.reshape(arr.shape)

    def _maybe_admit_locked(self, raw, admitted, evicted, sightings=1):
        """Admission path for an unseen-this-map id. Returns its row, or
        None when it stays cold (below threshold, or deferred because
        every resident row is pinned)."""
        n = self._pending.get(raw)
        if n is None:
            self._pending_order.append(raw)
            if len(self._pending_order) > self.max_pending:
                drop = self._pending_order.popleft()
                self._pending.pop(drop, None)
            n = 0
        n += int(sightings)
        if n < self.admit_count:
            self._pending[raw] = n
            return None
        row = self._claim_row_locked(evicted)
        if row is None:
            # full and nothing evictable: stay cold, keep the count so
            # the very next sighting retries admission
            self._pending[raw] = n
            self.deferred += 1
            return None
        self._pending.pop(raw, None)
        self._map.insert(raw, row)
        self.rows_admitted += 1
        admitted.append(raw)
        if self._log_moves:
            self._moves.append(('admit', raw, row))
        return row

    def _claim_row_locked(self, evicted):
        if self._free:
            return self._free.pop()
        victim = self._map.evict_one()   # LRU among unpinned residents
        if victim is None:
            return None
        old_id, old_row = victim
        self._resets.append(old_row)
        self.rows_evicted += 1
        evicted.append(old_id)
        if self._log_moves:
            self._moves.append(('evict', old_id, old_row))
        return old_row

    def _release(self, raw_ids):
        with self._lock:
            for raw in raw_ids:
                self._map.unref(raw)

    # -- explicit management ----------------------------------------------

    def preload(self, ids):
        """Admit `ids` immediately, in order (rows assigned ascending
        from the free list) — warm-starting a known hot set, and the
        identity mapping the static-vocab A/B drill trains through
        (cold_row=None, ids 0..capacity-1 -> rows 0..capacity-1)."""
        with self._lock:
            for raw in np.asarray(ids).reshape(-1):
                raw = int(raw)
                if raw in self._map:
                    continue
                if not self._free:
                    raise VocabFull(
                        'preload: no free row for id %d (capacity %d)'
                        % (raw, self.capacity))
                row = self._free.pop()
                self._map.insert(raw, row)
                self.rows_admitted += 1
                if self._log_moves:
                    self._moves.append(('admit', raw, row))
        return self

    def evict(self, raw_id):
        """Force one id out (admin/drill surface). Typed failures: a
        pinned row (in-flight gradient) raises RowPinned; an id that is
        not resident raises KeyError. The freed row joins the reset
        queue like any pressure eviction."""
        raw_id = int(raw_id)
        with self._lock:
            if raw_id not in self._map:
                raise KeyError('id %d is not resident in vocab %r'
                               % (raw_id, self.name))
            if self._map.refs(raw_id) > 0:
                raise RowPinned(
                    'id %d (vocab %r) is pinned by an in-flight batch — '
                    'its sparse gradient has not landed; evicting now '
                    'would tear the row. Release the lease first.'
                    % (raw_id, self.name))
            row = self._map.pop(raw_id)
            self._resets.append(row)
            # the freed row re-enters circulation (it used to leak:
            # a forced evict permanently lost one row of capacity)
            self._free.append(row)
            self.rows_evicted += 1
            if self._log_moves:
                self._moves.append(('evict', raw_id, row))
        _C_EVICTED.inc()
        obs.event('streaming.evict', vocab=self.name, rows=1,
                  sample=[raw_id], resident=len(self._map), forced=True)
        return row

    def drain_resets(self):
        """Rows evicted since the last drain — the trainer zeroes these
        (table + optimizer moments, RowResetter) BEFORE dispatching the
        step that trains their new owners."""
        with self._lock:
            out, self._resets = self._resets, []
        return out

    def drain_moves(self):
        """Ordered admission/eviction moves since the last drain —
        empty unless `_log_moves` was switched on by the tier store
        (`embedding.tiers.TieredVocabTable`), which turns evictions
        into SPILLS and warm admissions into RESTORES."""
        with self._lock:
            out, self._moves = self._moves, []
        return out

    def resident_ids(self):
        """Raw ids currently holding a private row, least recently used
        first (the eviction order)."""
        with self._lock:
            return [k for k, _ in self._map.items()]

    def rows_of(self, ids):
        """Resident rows for `ids` (ids not resident are skipped) —
        what the delta publisher pushes for a raw-id batch."""
        out = []
        with self._lock:
            for raw in np.asarray(ids).reshape(-1):
                row = self._map.get(int(raw))
                if row is not None:
                    out.append(row)
        return np.asarray(sorted(set(out)), np.int64)

    # -- checkpoint seam ---------------------------------------------------

    def state_dict(self):
        """JSON-able snapshot: the id->row map in RECENCY order (least
        recent first, so load rebuilds the same eviction order), pending
        counts, free rows, and the cumulative stats. Pins are NOT
        serialized — a checkpoint is taken at a step boundary, where no
        batch is in flight."""
        with self._lock:
            return {
                'capacity': self.capacity,
                'cold_row': self.cold_row,
                'admit_count': self.admit_count,
                'table': self.table,
                'entries': [[int(k), int(v)] for k, v in self._map.items()],
                'pending': [[int(k), int(self._pending[k])]
                            for k in self._pending_order
                            if k in self._pending],
                'free': [int(r) for r in self._free],
                'resets': [int(r) for r in self._resets],
                'stats': {'rows_admitted': self.rows_admitted,
                          'rows_evicted': self.rows_evicted,
                          'deferred': self.deferred,
                          'cold_hits': self.cold_hits,
                          'translations': self.translations},
            }

    def load_state_dict(self, state):
        """Exact-resume restore (the inverse of state_dict). The
        geometry (capacity/cold_row) must match the table this map
        fronts — a checkpoint from a different table shape fails typed
        instead of silently mis-mapping rows."""
        if int(state['capacity']) != self.capacity or \
                state.get('cold_row') != self.cold_row:
            raise ValueError(
                'vocab %r: checkpoint geometry (capacity=%s cold_row=%s) '
                'does not match this table (capacity=%d cold_row=%s)'
                % (self.name, state.get('capacity'), state.get('cold_row'),
                   self.capacity, self.cold_row))
        with self._lock:
            self._map = RefCountedLRU()
            for k, v in state.get('entries', []):
                self._map.insert(int(k), int(v))
            self._pending = {int(k): int(n)
                             for k, n in state.get('pending', [])}
            self._pending_order = collections.deque(
                int(k) for k, _ in state.get('pending', []))
            self._free = [int(r) for r in state.get('free', [])]
            self._resets = [int(r) for r in state.get('resets', [])]
            st = state.get('stats', {})
            self.rows_admitted = int(st.get('rows_admitted', 0))
            self.rows_evicted = int(st.get('rows_evicted', 0))
            self.deferred = int(st.get('deferred', 0))
            self.cold_hits = int(st.get('cold_hits', 0))
            self.translations = int(st.get('translations', 0))
        return self

    def stats(self):
        with self._lock:
            return {'resident': len(self._map), 'free': len(self._free),
                    'capacity': self.capacity,
                    'pending': len(self._pending),
                    'rows_admitted': self.rows_admitted,
                    'rows_evicted': self.rows_evicted,
                    'deferred': self.deferred,
                    'cold_hits': self.cold_hits,
                    'translations': self.translations}

    def __len__(self):
        with self._lock:
            return len(self._map)


def table_state_names(program, table):
    """The persistable names eviction must zero for `table`: the table
    itself plus every same-shape optimizer accumulator its optimizer op
    reads (adam moments, adagrad moment, momentum velocity — anything
    vocab-sized; scalar state like beta pows is excluded by the shape
    filter). Walked from the program so the trainer never hard-codes an
    optimizer's accumulator naming."""
    blk = program.global_block()
    tvar = blk.vars.get(table)
    if tvar is None:
        raise KeyError('no variable %r in the program' % (table,))
    shape = tuple(int(d) for d in tvar.shape)
    names = [table]
    for op in blk.ops:
        params = op.inputs.get('Param') or []
        if not any(v.name == table for v in params):
            continue
        for slot, vs in op.inputs.items():
            if slot in ('Param', 'Grad', 'LearningRate'):
                continue
            for v in vs:
                if (getattr(v, 'persistable', False)
                        and tuple(int(d) for d in v.shape) == shape
                        and v.name not in names):
                    names.append(v.name)
    return names


class RowResetter(object):
    """Zero evicted rows of a table and its optimizer moments as ONE
    fixed-shape jitted scatter.

    The reset list length varies per step; the jitted signature must
    not (zero steady-state compiles). Rows are padded to a fixed
    `batch` with the out-of-range index `capacity` and scattered with
    mode='drop' — padding writes nothing. Longer lists loop. Arrays are
    donated (in-place on real chips) and a NamedSharding input keeps
    its layout pinned on the output, so a mesh-sharded table's reset
    neither gathers nor resharsds anything."""

    def __init__(self):
        self._fns = {}    # (n_arrays, shapes, dtypes, batch) -> jitted

    @staticmethod
    def _signature(arrays, batch):
        return (tuple((tuple(a.shape), str(a.dtype)) for a in arrays),
                int(batch))

    def _fn(self, arrays, batch):
        import jax
        import jax.numpy as jnp
        sig = self._signature(arrays, batch)
        fn = self._fns.get(sig)
        if fn is None:
            from jax.sharding import NamedSharding
            shardings = [a.sharding if isinstance(a, jax.Array)
                         and isinstance(getattr(a, 'sharding', None),
                                        NamedSharding) else None
                         for a in arrays]

            def reset(arrs, rows):
                out = []
                for a, sh in zip(arrs, shardings):
                    z = a.at[rows].set(jnp.zeros((), a.dtype),
                                       mode='drop')
                    if sh is not None:
                        z = jax.lax.with_sharding_constraint(z, sh)
                    out.append(z)
                return out

            fn = jax.jit(reset, donate_argnums=0)
            self._fns[sig] = fn
        return fn

    def reset(self, arrays, rows, batch=256):
        """Zero `rows` of every array in `arrays` (list of same-leading-
        dim device/np arrays). Returns the new arrays, input order."""
        import jax.numpy as jnp
        rows = [int(r) for r in rows]
        if not rows:
            return list(arrays)
        cap = int(arrays[0].shape[0])
        arrays = [a if hasattr(a, 'dtype') else np.asarray(a)
                  for a in arrays]
        fn = self._fn(arrays, batch)
        for lo in range(0, len(rows), batch):
            chunk = rows[lo:lo + batch]
            padded = chunk + [cap] * (batch - len(chunk))
            arrays = fn(arrays, jnp.asarray(padded, jnp.int32))
        return list(arrays)
