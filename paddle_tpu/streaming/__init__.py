"""paddle_tpu.streaming — online training over unbounded id streams.

The production loop the reference's pserver era actually served,
rebuilt TPU-native (docs/embedding.md "streaming ids"): a recommender
trains on a click stream whose id space drifts, while its parameters
continuously publish to live serving. Three legs:

  * :class:`VocabTable` — host-side raw-id -> row indirection with
    frequency admission (cold-row training below the threshold) and
    LRU eviction of unpinned rows, so the COMPILED step's table shape
    never changes as the vocab drifts;
  * `Trainer.train_stream` (fluid/trainer.py) — the unbounded-stream
    hot loop: prefetch, translation, evicted-row zeroing, step/
    wall-clock checkpoint cadence with the vocab serialized into the
    checkpoint meta;
  * :class:`DeltaPublisher` — touched-row snapshots pushed into
    running `ServingEngine`/`DecodeEngine` replicas via
    `Router.push_deltas` — per-row scatter instead of full-artifact
    swap().

The HBM capacity ceiling behind `VocabTable` is lifted by the TIER
STORE (`paddle_tpu.embedding.tiers`, docs/embedding.md#tiers):
`TieredVocabTable` + `HostArena` spill evicted rows (+ optimizer
moments) to host RAM and restore them bit-exactly on re-admission —
re-exported here because they duck-type the `VocabTable` surface this
package defines.
"""
from .publish import DeltaPublisher
from .vocab import (Lease, RowPinned, RowResetter, VocabFull, VocabTable,
                    table_state_names)
from ..embedding.tiers import (ArenaCorrupt, ArenaFull,
                               DimShardingUnsupported, HostArena,
                               TieredVocabTable, host_arena)

__all__ = ['VocabTable', 'DeltaPublisher', 'RowResetter', 'Lease',
           'RowPinned', 'VocabFull', 'table_state_names',
           'TieredVocabTable', 'HostArena', 'ArenaFull', 'ArenaCorrupt',
           'DimShardingUnsupported', 'host_arena']
