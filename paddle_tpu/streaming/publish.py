"""Delta publishing: touched-row snapshots pushed into live serving.

The reference's pserver loop closed train->serve freshness by having
serving read the same parameter-server shards training wrote. Here the
two sides are separate processes-worth of state (the Trainer's scope vs
a Router's replicas), and the freshness loop closes with ROW DELTAS:
the sparse update path already knows exactly which table rows a step
wrote (`StepArtifact.touched_rows` — resolved host-side from the feed,
docs/embedding.md), so :class:`DeltaPublisher` accumulates that touched
set off the step path, snapshots the rows' current values at its
cadence, and pushes them into every live replica through
`Router.push_deltas` — per-row scatter into the running engine instead
of a full-artifact `swap()`.

Failure posture: the pending (touched) set clears ONLY on a successful
push. A push that fails — host loss surfacing through the PR 10
heartbeat, every replica refusing, an IO error — leaves the set intact,
so the next cadence retries the SAME rows (plus whatever accumulated
since); freshness degrades, correctness never does. Host loss fails
TYPED (`parallel.heartbeat.HostLost`) before any replica is touched, so
a push can never half-land across a dying pod.

Measured: `streaming.delta_push` events carry rows/tables/push_ms and
the freshness lag (now minus the OLDEST unpushed touch — the staleness
a scoring request could have observed), with
`streaming.freshness_lag_s` as a gauge; `bench.py --phase streaming`
reports both (docs/embedding.md "streaming ids").
"""
import threading
import time

import numpy as np

from .. import obs
from ..obs import trace

__all__ = ['DeltaPublisher']

_G_LAG = obs.gauge('streaming.freshness_lag_s')
_C_PUSHES = obs.counter('streaming.delta_pushes')
_C_PUSH_ROWS = obs.counter('streaming.delta_rows')
_G_PUSH_BYTES = obs.gauge('streaming.delta_push_bytes')


class DeltaPublisher(object):
    """Accumulate touched rows per table; push their live values.

    router/model_id: the serving side (`Router.push_deltas`). Pass
        `router=engine_like` with a `push_rows` method and
        `model_id=None` to push straight into one engine (tests,
        single-replica deployments).
    interval_steps / min_interval_s: the cadence — a publish fires when
        BOTH at least `interval_steps` collected steps and
        `min_interval_s` seconds have passed since the last push.
    name_map: training table name -> serving persistable name (tables
        keep their names through clone/save_inference_model, so the
        default identity map is usually right).
    heartbeat: a `parallel.Heartbeat` checked immediately before every
        push — a stale peer raises the typed HostLost BEFORE any
        replica is touched (deltas retained for the survivor's retry).
    quant: None (fp32 rows, the default) or 'int8' — push each row as
        int8 + one f32 per-row scale (embedding.quant_rows), cutting
        value bytes per row from 4*D to D+4 (docs/perf.md). A router
        with `push_quantized_rows`/`push_quantized_deltas` receives the
        codec form (rows, q, scale) and dequantizes replica-side;
        otherwise the publisher dequantizes locally and pushes fp32
        through the normal methods — the replica then holds exactly the
        values a quantized wire would have delivered (the documented
        rounding: <= max|row|/254 per element). `last_push_bytes` and
        the `streaming.delta_push_bytes` gauge record the VALUE payload
        either way — the bench.py --phase quant A/B metric.
    """

    def __init__(self, router, model_id=None, interval_steps=1,
                 min_interval_s=0.0, name_map=None, heartbeat=None,
                 quant=None):
        if quant not in (None, 'int8'):
            raise ValueError("quant must be None or 'int8', got %r"
                             % (quant,))
        self._router = router
        self._model_id = model_id
        self.interval_steps = int(interval_steps)
        self.min_interval_s = float(min_interval_s)
        self._name_map = dict(name_map or {})
        self._heartbeat = heartbeat
        self.quant = quant
        self._lock = threading.Lock()
        self._pending = {}        # table -> set of touched rows
        self._oldest_touch = None  # monotonic time of oldest unpushed touch
        self._steps_since = 0
        self._last_push_t = None
        # cumulative stats (bench + the obs_report streaming section)
        self.pushes = 0
        self.failed_pushes = 0
        self.rows_pushed = 0
        self.last_lag_s = None
        self.last_push_ms = None
        self.last_push_bytes = None

    def collect(self, touched, step=None):
        """Record one step's touched rows: {table: int row ids} — the
        shape `StepArtifact.touched_rows(feed)` returns. Cheap host
        set-union; never touches the device."""
        now = time.monotonic()
        with self._lock:
            for table, rows in touched.items():
                rows = np.asarray(rows).reshape(-1)
                if not rows.size:
                    continue
                s = self._pending.get(table)
                if s is None:
                    s = self._pending[table] = set()
                s.update(int(r) for r in rows)
                if self._oldest_touch is None:
                    self._oldest_touch = now
            self._steps_since += 1

    def pending_rows(self):
        with self._lock:
            return {t: len(s) for t, s in self._pending.items()}

    def due(self):
        """Is the cadence satisfied? (Something pending, enough steps,
        enough wall clock.)"""
        with self._lock:
            if not self._pending:
                return False
            if self._steps_since < self.interval_steps:
                return False
            if self._last_push_t is not None and self.min_interval_s > 0 \
                    and time.monotonic() - self._last_push_t \
                    < self.min_interval_s:
                return False
            return True

    def maybe_publish(self, read_table):
        """publish() when due; returns rows pushed (0 when not due)."""
        if not self.due():
            return 0
        return self.publish(read_table)

    def publish(self, read_table):
        """Snapshot every pending table's touched rows through
        `read_table(name) -> array-like` (the trainer passes a scope
        reader; a mesh-sharded table gathers ONLY the touched rows) and
        push them into the live replicas. Clears the pending set on
        success only. Returns rows pushed."""
        import jax.numpy as jnp
        # each publish is its own trace (continuing the caller's when
        # inside one): the events below AND the remote workers' apply
        # spans — the wire proxies forward the context — stitch into one
        # cross-host timeline per push
        ctx = trace.current()
        if ctx is None:
            ctx = trace.new_trace()
        h = trace.begin('streaming.publish', ctx=ctx, node='publisher')
        with trace.activate(h.ctx if h is not None else ctx,
                            node='publisher'):
            try:
                total = self._publish(read_table, jnp)
            except Exception as e:
                if h is not None:
                    h.end(error=type(e).__name__)
                raise
        if h is not None:
            h.end(rows=total)
        return total

    def _publish(self, read_table, jnp):
        if self._heartbeat is not None:
            # typed host-loss gate BEFORE any replica mutates: a push
            # must never half-land across a dying pod
            self._heartbeat.check(raise_error=True)
        with self._lock:
            snapshot = {t: np.asarray(sorted(s), np.int64)
                        for t, s in self._pending.items()}
            oldest = self._oldest_touch
        if not snapshot:
            return 0
        deltas = {}
        total = 0
        push_bytes = 0
        quantized_wire = False
        if self.quant == 'int8':
            from ..embedding import quant_rows as qr
            # codec-aware router: ship (rows, q, scale); otherwise
            # dequantize here and push fp32 carrying the SAME values a
            # quantized wire delivers (rounding documented on `quant`)
            quantized_wire = hasattr(
                self._router, 'push_quantized_deltas'
                if self._model_id is not None else 'push_quantized_rows')
        for table, rows in snapshot.items():
            w = read_table(table)
            vals = np.asarray(jnp.take(jnp.asarray(w),
                                       jnp.asarray(rows), axis=0))
            name = self._name_map.get(table, table)
            if self.quant == 'int8':
                q, scale = qr.quantize_rows(vals)
                push_bytes += qr.row_bytes(q, scale)
                if quantized_wire:
                    deltas[name] = (rows, q, scale)
                else:
                    deltas[name] = (rows, qr.dequantize_rows(q, scale))
            else:
                deltas[name] = (rows, vals)
                push_bytes += int(vals.nbytes)
            total += int(rows.size)
        t0 = time.monotonic()
        try:
            if self._model_id is not None:
                if quantized_wire:
                    self._router.push_quantized_deltas(self._model_id,
                                                       deltas)
                else:
                    self._router.push_deltas(self._model_id, deltas)
            elif quantized_wire:
                self._router.push_quantized_rows(deltas)
            else:
                self._router.push_rows(deltas)
        except Exception:
            # pending set stays intact: the next cadence retries these
            # rows (freshness degrades, correctness never does)
            self.failed_pushes += 1
            obs.event('streaming.delta_push', ok=False, rows=total,
                      tables=sorted(snapshot))
            raise
        now = time.monotonic()
        push_ms = (now - t0) * 1000.0
        lag_s = (now - oldest) if oldest is not None else 0.0
        with self._lock:
            # drop exactly what was pushed; rows touched DURING the push
            # stay pending for the next cadence
            for table, rows in snapshot.items():
                s = self._pending.get(table)
                if s is not None:
                    s.difference_update(int(r) for r in rows)
                    if not s:
                        self._pending.pop(table)
            self._oldest_touch = time.monotonic() if self._pending else None
            self._steps_since = 0
            self._last_push_t = now
        self.pushes += 1
        self.rows_pushed += total
        self.last_lag_s = lag_s
        self.last_push_ms = push_ms
        self.last_push_bytes = push_bytes
        _C_PUSHES.inc()
        _C_PUSH_ROWS.inc(total)
        _G_LAG.set(lag_s)
        _G_PUSH_BYTES.set(push_bytes)
        obs.event('streaming.delta_push', ok=True, rows=total,
                  tables=sorted(snapshot), push_ms=round(push_ms, 3),
                  push_bytes=push_bytes, quant=self.quant or 'fp32',
                  freshness_lag_s=round(lag_s, 4))
        return total

    def stats(self):
        with self._lock:
            pending = sum(len(s) for s in self._pending.values())
        return {'pushes': self.pushes,
                'failed_pushes': self.failed_pushes,
                'rows_pushed': self.rows_pushed,
                'pending_rows': pending,
                'last_freshness_lag_s': self.last_lag_s,
                'last_push_ms': self.last_push_ms,
                'last_push_bytes': self.last_push_bytes,
                'quant': self.quant or 'fp32'}
