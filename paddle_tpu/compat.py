"""String/number compat helpers.

Parity: reference python/paddle/compat.py (to_text/to_bytes container-aware
codecs, py2-style round-half-away-from-zero, floor_division,
get_exception_message). Python-3 native — the py2 branches collapse.
"""
import math

__all__ = [
    'long_type',
    'to_text',
    'to_bytes',
    'round',
    'floor_division',
    'get_exception_message',
]

int_type = int
long_type = int


def _decode_one(obj, encoding):
    # non-bytes objects pass through unchanged (the reference's six.u is
    # an identity on py3 text; ints/tuples/etc. must not be repr-coerced)
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return obj


def to_text(obj, encoding='utf-8', inplace=False):
    """Decode obj (or every item of a list/set obj) to str."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_decode_one(v, encoding) for v in obj]
            return obj
        return [_decode_one(v, encoding) for v in obj]
    if isinstance(obj, set):
        decoded = {_decode_one(v, encoding) for v in obj}
        if inplace:
            obj.clear()
            obj.update(decoded)
            return obj
        return decoded
    return _decode_one(obj, encoding)


def _encode_one(obj, encoding):
    assert encoding is not None
    if isinstance(obj, str):
        return obj.encode(encoding)
    # bytes as-is; other objects pass through unchanged (see _decode_one)
    return obj


def to_bytes(obj, encoding='utf-8', inplace=False):
    """Encode obj (or every item of a list/set obj) to bytes."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_encode_one(v, encoding) for v in obj]
            return obj
        return [_encode_one(v, encoding) for v in obj]
    if isinstance(obj, set):
        encoded = {_encode_one(v, encoding) for v in obj}
        if inplace:
            obj.clear()
            obj.update(encoded)
            return obj
        return encoded
    return _encode_one(obj, encoding)


def round(x, d=0):
    """Round half away from zero (python2 semantics; python3's builtin
    rounds half to even)."""
    p = 10 ** d
    if x > 0.0:
        return float(math.floor(x * p + 0.5)) / p
    if x < 0.0:
        return float(math.ceil(x * p - 0.5)) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    assert exc is not None
    return str(exc)
