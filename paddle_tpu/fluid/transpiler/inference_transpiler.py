"""Inference transpiler.

Parity: reference transpiler/inference_transpiler.py — fuses batch_norm into
the preceding conv for inference. On TPU, XLA already fuses BN-scale into
convolutions at compile time, so the transform is mostly redundant; we still
perform the graph-level fold (conv+BN -> conv with adjusted weights) so the
resulting program is smaller and matches reference behavior.
"""
import numpy as np

__all__ = ['InferenceTranspiler']


class InferenceTranspiler(object):
    def transpile(self, program, place=None, scope=None):
        """Fold batch_norm (is_test) into a preceding conv2d when the conv
        output has no other consumer. Mutates program in place."""
        from ..executor import global_scope
        import jax.numpy as jnp
        if scope is None:
            scope = global_scope()
        block = program.global_block()
        i = 0
        while i < len(block.ops) - 1:
            op = block.ops[i]
            nxt = block.ops[i + 1]
            if op.type == 'conv2d' and nxt.type == 'batch_norm' and \
                    nxt.inputs['X'][0].name == op.outputs['Output'][0].name:
                scale_v = scope.vars.get(nxt.inputs['Scale'][0].name)
                bias_v = scope.vars.get(nxt.inputs['Bias'][0].name)
                mean_v = scope.vars.get(nxt.inputs['Mean'][0].name)
                var_v = scope.vars.get(nxt.inputs['Variance'][0].name)
                w_name = op.inputs['Filter'][0].name
                w = scope.vars.get(w_name)
                if any(v is None for v in (scale_v, bias_v, mean_v, var_v, w)):
                    i += 1
                    continue
                eps = nxt.attrs.get('epsilon', 1e-5)
                scale = np.asarray(scale_v)
                bias = np.asarray(bias_v)
                mean = np.asarray(mean_v)
                var = np.asarray(var_v)
                wnp = np.asarray(w)
                inv = scale / np.sqrt(var + eps)
                scope.vars[w_name] = jnp.asarray(
                    wnp * inv[:, None, None, None])
                # new bias var feeding an elementwise_add after conv
                new_bias = bias - mean * inv
                bias_var = block.create_var(
                    name=w_name + '.bnfold_bias', shape=list(new_bias.shape),
                    dtype='float32', persistable=True)
                scope.vars[bias_var.name] = jnp.asarray(new_bias)
                bn_out = nxt.outputs['Y'][0]
                op.outputs['Output'] = [op.outputs['Output'][0]]
                block.ops[i + 1] = block.ops[i + 1]
                # replace bn op with add op
                from ..framework import Operator
                # channel axis follows the conv's layout
                ch_axis = (-1 if op.attrs.get('data_format',
                                              'NCHW') == 'NHWC' else 1)
                add_op = Operator(block, type='elementwise_add',
                                  inputs={'X': op.outputs['Output'],
                                          'Y': [bias_var]},
                                  outputs={'Out': [bn_out]},
                                  attrs={'axis': ch_axis})
                block.ops[i + 1] = add_op
                program._bump_version()
            i += 1
        return program
