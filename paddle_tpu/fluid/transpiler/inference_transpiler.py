"""Inference transpiler — DEPRECATED shim over fluid.passes.

Parity: reference transpiler/inference_transpiler.py — fuses batch_norm
into the preceding conv for inference. The graph walk now lives in
`fluid.passes.fold.fold_batch_norm` (the constant-folding pass's
scope-weight sibling); this class remains as the reference-API surface
and simply delegates (docs/migration.md). For the rest of what the
reference transpiler family did ahead of execution — dead-op pruning,
constant folding, CSE — use `PADDLE_TPU_OPT` / `Program.optimize()`.
"""
import warnings

__all__ = ['InferenceTranspiler']


class InferenceTranspiler(object):
    def transpile(self, program, place=None, scope=None):
        """Fold batch_norm (is_test) into a preceding conv2d when the conv
        output has no other consumer. Mutates program in place."""
        warnings.warn(
            'InferenceTranspiler is deprecated: the conv+BN fold lives in '
            'fluid.passes.fold.fold_batch_norm, and the general '
            'ahead-of-lowering optimizations in PADDLE_TPU_OPT / '
            'Program.optimize(). See docs/migration.md.',
            DeprecationWarning, stacklevel=2)
        from ..executor import global_scope
        from ..passes.fold import fold_batch_norm
        if scope is None:
            scope = global_scope()
        fold_batch_norm(program, scope)
        return program
