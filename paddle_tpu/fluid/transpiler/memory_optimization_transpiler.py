"""Memory-optimization transpiler.

Parity: reference transpiler/memory_optimization_transpiler.py, which does
liveness analysis over the ProgramDesc and reuses var buffers.

TPU-first redesign: XLA's buffer assignment already performs liveness-based
reuse inside the fused step, so per-op buffer aliasing is moot. What still
matters on TPU is *activation memory across the fwd/bwd boundary* — the
equivalent lever is rematerialisation: memory_optimize() flags the program
so the Executor wraps the forward trace in jax.checkpoint, trading FLOPs
for HBM exactly where the reference traded buffer reuse.
"""
__all__ = ['memory_optimize', 'release_memory']


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    input_program._use_remat = True
    if print_log:
        print("memory_optimize: forward will be rematerialised "
              "(jax.checkpoint) in the compiled step")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    """XLA frees the arena between steps automatically; no-op."""
    return input_program
