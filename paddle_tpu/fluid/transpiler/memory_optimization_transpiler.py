"""Memory-optimization transpiler — DEPRECATED shim over fluid.passes.

Parity: reference transpiler/memory_optimization_transpiler.py, which did
liveness analysis over the ProgramDesc and reused var buffers.

The TPU-native equivalents now live elsewhere (docs/migration.md):
  * per-op buffer reuse — XLA's buffer assignment inside the fused step,
    plus the per-program donation/memory plan (`fluid.passes.memory_plan`)
    that donates exactly the written persistables so updates alias in
    place in HBM;
  * activation memory across the fwd/bwd boundary — rematerialisation:
    this shim still flags the program so the Executor wraps the forward
    trace in jax.checkpoint, trading FLOPs for HBM exactly where the
    reference traded buffer reuse;
  * dead-op/liveness pruning — `PADDLE_TPU_OPT` / `Program.optimize()`
    (fluid.passes.dce), which retired this module's graph walk.
"""
import warnings

__all__ = ['memory_optimize', 'release_memory']


def memory_optimize(input_program, skip_opt_set=None, print_log=False,
                    level=0):
    warnings.warn(
        'memory_optimize() is deprecated: buffer reuse is owned by the '
        'donation/memory plan (fluid.passes.memory_plan) and dead-op '
        'pruning by PADDLE_TPU_OPT / Program.optimize(); this call now '
        'only flags the forward for rematerialisation (jax.checkpoint). '
        'See docs/migration.md.', DeprecationWarning, stacklevel=2)
    input_program._use_remat = True
    if print_log:
        print("memory_optimize: forward will be rematerialised "
              "(jax.checkpoint) in the compiled step")
    return input_program


def release_memory(input_program, skip_opt_set=None):
    """XLA frees the arena between steps automatically; no-op."""
    return input_program
