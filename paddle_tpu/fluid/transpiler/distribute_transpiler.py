"""Distributed training transpiler.

Parity: reference python/paddle/fluid/transpiler/distribute_transpiler.py,
which splits the program into trainer programs (send/recv gradient ops over
gRPC) and parameter-server programs (optimizer ops moved server-side).

TPU-first redesign: parameter servers do not exist on a TPU pod — gradients
ride the ICI mesh as XLA all-reduces, and multi-host scaling is the same
GSPMD program over a larger mesh (paddle_tpu.parallel.init_multihost →
jax.distributed). transpile() annotates the program with the mesh geometry
(`_dist_config`); the Executor CONSUMES that annotation: it builds the dp
mesh, replicates parameters, shards feed batches, and — the pserver memory
story — ZeRO-shards optimizer accumulators over dp with the shardings
enforced inside the compiled step (slice_var_up=True maps to the
reference's splitting of large vars across pservers). get_trainer_program()
returns the annotated program; get_pserver_program(endpoint) returns the
SAME annotated program with that endpoint's shard coordinate recorded —
on TPU every process is both trainer and owner of its optimizer shard, so
reference launcher scripts that spawn one pserver per endpoint end up
launching mesh participants.
"""
from ..framework import Program, default_main_program

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig']


class DistributeTranspilerConfig(object):
    """Transpile knobs (reference distribute_transpiler.py:116).

    slice_var_up: reference splits large vars across pservers; here it maps
        to ZeRO-sharding optimizer state over the dp mesh axis.
    shard_parameters: ZeRO-3/FSDP — shard the parameters THEMSELVES over
        dp (parallel.fsdp_shard_params; GSPMD gathers at use). The closest
        analogue of the reference actually splitting parameter blocks
        across pservers. Off by default (replicated params).
    split_method: pserver load-balancing dispatcher (RoundRobin/HashName) —
        kept for API compat; shard placement on TPU is GSPMD's job.
    min_block_size: minimum split block size — advisory only here.
    """

    slice_var_up = True
    shard_parameters = False
    split_method = None
    min_block_size = 8192


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self._config = config if config is not None \
            else DistributeTranspilerConfig()
        self._trainers = 1
        self._trainer_id = 0
        self._program = None
        self._sync_mode = True

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  slice_var_up=True, split_method=None):
        """Record the topology and annotate the program with the dp mesh
        size. trainer_id/trainers map onto mesh coordinates.

        DEPRECATED shim (docs/migration.md, docs/embedding.md): the
        pserver topology this API described is now two first-class
        Program concerns — `Program.set_mesh({...})` for the mesh and
        `ParamAttr(sharding=...)` for per-tensor placement; the pserver
        ROW SPLIT of huge embedding tables specifically is
        `embedding(is_sparse=True, is_distributed=True)` with the table
        annotated `sharding=('dp', None)` (the all_to_all lookup wire +
        sharded sparse updates replace gRPC prefetch + pserver-side
        optimizer blocks). This call still arms the legacy dp-mesh
        executor path AND translates its embedding intent forward: any
        table read by an `is_distributed=True` lookup gets the row-
        sharding annotation stamped here, so dropping the transpile()
        call and declaring set_mesh() is the whole migration."""
        import warnings
        warnings.warn(
            'DistributeTranspiler is deprecated: declare the mesh with '
            "Program.set_mesh({'dp': N, ...}) and shard huge embedding "
            "tables with ParamAttr(sharding=('dp', None)) + "
            'embedding(is_sparse=True, is_distributed=True) — the '
            'sharded-embedding subsystem (docs/embedding.md) replaces '
            'the pserver row split; see the migration table in '
            'docs/migration.md.', DeprecationWarning, stacklevel=2)
        if program is None:
            program = default_main_program()
        self._annotate_distributed_tables(program)
        if isinstance(pservers, str):
            pserver_endpoints = [e for e in pservers.split(",") if e]
        else:
            pserver_endpoints = list(pservers)
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._program = program
        self._startup_program = startup_program
        self._sync_mode = sync_mode
        self._pserver_endpoints = pserver_endpoints
        # MERGE into any existing annotation (SequenceParallelTranspiler /
        # PipelineTranspiler may have run first — clobbering would silently
        # drop their axes) and force the mesh to rebuild
        base = dict(getattr(program, '_dist_config', None) or {})
        base.update({
            'dp_size': trainers,
            'trainer_id': trainer_id,
            'sync_mode': sync_mode,
            # reference slice_var_up split big vars across pservers; the
            # TPU equivalent is ZeRO-sharding optimizer state over dp
            'shard_optimizer_states': bool(
                slice_var_up and getattr(self._config, 'slice_var_up', True)),
            'shard_parameters': bool(
                getattr(self._config, 'shard_parameters', False)),
        })
        # recompute from the MERGED sizes so an earlier pipeline/sp/tp
        # transpile keeps its axis in the annotation instead of being
        # clobbered to a dp-only claim
        from ._mesh_axes import rebuild_mesh_axes
        base['mesh_axes'] = rebuild_mesh_axes(base)
        program._dist_config = base
        program._dist_mesh = None
        return self

    @staticmethod
    def _annotate_distributed_tables(program, axis='dp'):
        """Translate the pserver embedding intent into the first-class
        surface: every table read by an `is_distributed=True`
        lookup_table gets `sharding=(axis, None)` stamped (and the op its
        `dist_axis` routing attr), so the SAME program runs the all_to_all
        lookup wire the moment it is driven through `set_mesh()` instead
        of this shim. Already-annotated tables are left alone; the legacy
        `_dist_config` executor path ignores the annotation except to
        preserve it across reloads (_replace_strays)."""
        from ..framework import normalize_sharding
        ops = [op for blk in program.blocks for op in blk.ops]
        for op in ops:
            # every block: the decode idiom puts lookups inside While
            # sub-blocks (analysis._embedding_tables walks the same way)
            if op.type != 'lookup_table' \
                    or not op.attrs.get('is_distributed'):
                continue
            w = op.inputs['W'][0]
            if getattr(w, 'sharding', None) is None:
                ndim = len(w.shape) if w.shape is not None else 2
                w.sharding = normalize_sharding(
                    (axis,) + (None,) * (ndim - 1))
            row = w.sharding[0]
            if op.attrs.get('dist_axis') is None \
                    and row is not None and not isinstance(row, tuple):
                op.attrs['dist_axis'] = row

    def get_trainer_program(self):
        """The trainer program IS the original program — GSPMD shards it
        over the mesh at jit time (no send/recv op rewriting)."""
        return self._program

    def get_pserver_program(self, endpoint):
        """On a TPU mesh every process is simultaneously a trainer and the
        'parameter server' of its own ZeRO optimizer-state shard. Launcher
        scripts that start one pserver process per endpoint therefore get
        the SAME mesh-annotated program back, with this endpoint's shard
        coordinate recorded — running it joins the mesh as the owner of
        that optimizer shard (reference instead rewrites the program into
        recv/optimize/send blocks, distribute_transpiler.py:471)."""
        if self._program is None:
            raise RuntimeError('call transpile() before get_pserver_program')
        try:
            idx = self._pserver_endpoints.index(endpoint)
        except ValueError:
            raise ValueError('unknown pserver endpoint %r (transpiled with '
                             '%r)' % (endpoint, self._pserver_endpoints))
        prog = self._program.clone()
        prog._dist_config = dict(self._program._dist_config,
                                 shard_owner=idx,
                                 n_shard_owners=len(self._pserver_endpoints))
        return prog

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), self.get_startup_program(
            endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        """The mesh participant runs the ordinary startup program (params
        replicate at first use): the one passed here, else the one recorded
        at transpile() time, else the thread default."""
        if startup_program is not None:
            return startup_program
        if getattr(self, '_startup_program', None) is not None:
            return self._startup_program
        from ..framework import default_startup_program
        return default_startup_program()
