"""Distributed training transpiler.

Parity: reference python/paddle/fluid/transpiler/distribute_transpiler.py,
which splits the program into trainer programs (send/recv gradient ops over
gRPC) and parameter-server programs (optimizer ops moved server-side).

TPU-first redesign: parameter servers do not exist on a TPU pod — gradients
ride the ICI mesh as XLA all-reduces, and multi-host scaling is the same
GSPMD program over a larger mesh (paddle_tpu.parallel.init_multihost →
jax.distributed). transpile() annotates the program with the mesh geometry
(`_dist_config`); the Executor CONSUMES that annotation: it builds the dp
mesh, replicates parameters, shards feed batches, and — the pserver memory
story — ZeRO-shards optimizer accumulators over dp with the shardings
enforced inside the compiled step (slice_var_up=True maps to the
reference's splitting of large vars across pservers). get_trainer_program()
returns the annotated program; get_pserver_program() returns a no-op
program so reference launcher scripts degrade gracefully.
"""
from ..framework import Program, default_main_program

__all__ = ['DistributeTranspiler']


class DistributeTranspiler(object):
    def __init__(self, config=None):
        self._config = config
        self._trainers = 1
        self._trainer_id = 0
        self._program = None
        self._sync_mode = True

    def transpile(self, trainer_id, program=None, pservers="127.0.0.1:6174",
                  trainers=1, sync_mode=True, startup_program=None,
                  slice_var_up=True, split_method=None):
        """Record the topology and annotate the program with the dp mesh
        size. trainer_id/trainers map onto mesh coordinates."""
        if program is None:
            program = default_main_program()
        if isinstance(pservers, str):
            pserver_endpoints = [e for e in pservers.split(",") if e]
        else:
            pserver_endpoints = list(pservers)
        self._trainer_id = trainer_id
        self._trainers = trainers
        self._program = program
        self._sync_mode = sync_mode
        self._pserver_endpoints = pserver_endpoints
        program._dist_config = {
            'mesh_axes': ('dp',),
            'dp_size': trainers,
            'trainer_id': trainer_id,
            'sync_mode': sync_mode,
            # reference slice_var_up split big vars across pservers; the
            # TPU equivalent is ZeRO-sharding optimizer state over dp
            'shard_optimizer_states': bool(slice_var_up),
        }
        return self

    def get_trainer_program(self):
        """The trainer program IS the original program — GSPMD shards it
        over the mesh at jit time (no send/recv op rewriting)."""
        return self._program

    def get_pserver_program(self, endpoint):
        """No parameter server exists on TPU; return an empty program so
        reference launcher scripts that start pserver processes degrade
        gracefully."""
        return Program()

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint), Program()

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None):
        return Program()
