"""Sequence-parallel transpiler: long-context Fluid programs over `sp`.

TPU-first extension (no reference counterpart — the reference caps context
by single-GPU memory; benchmark/fluid machine_translation max_length).
Annotates the program so the Executor builds a mesh with an `sp` axis;
every `fused_attention` op in the program then routes through
parallel.ring_attention (ops_impl/nn_ops.py:_flash_attention): k/v shards
rotate around the ICI ring, each device holding O(T/sp) keys, with the
pallas flash kernel as the per-step block on TPU. Attention is the O(T^2)
term, so this is where long-context memory and compute distribute; the
pointwise/ffn ops stay data-parallel-shaped and XLA propagates shardings
through them.

    avg_cost, _, feeds = transformer(..., max_length=32768)
    fluid.SequenceParallelTranspiler(sp=8).transpile(main_program)
    exe.run(main_program, ...)          # attention rides the sp ring

Composes with DistributeTranspiler (dp) — axis sizes multiply, so dp x sp
needs dp*sp visible devices, each dp replica running its own ring over its
batch slice. Composes with PipelineTranspiler (pp) too: the pipeline
region's shard_map is manual over dp/pp AND sp — pipeline_apply shards the
activation's sequence dim over 'sp', stage bodies run sequence-local, and
the attention lowering detects the manual context (ctx.manual_axes) and
calls the per-shard ring/ulysses collective body instead of opening its
own shard_map.
"""
from ..framework import default_main_program

__all__ = ['SequenceParallelTranspiler']


class SequenceParallelTranspiler(object):
    """The mesh axis is fixed to 'sp' — the fused_attention lowering routes
    by that name (ops_impl/nn_ops.py).

    strategy: 'ring' (ppermute ring, O(T/sp) keys per device — extreme
        context) or 'ulysses' (two all_to_alls re-partitioning to head
        sharding — needs heads % sp == 0 and full T on-device for scores;
        cheaper comm when heads are plentiful). Stamped on each
        fused_attention op, so the choice serializes with the program.
    """

    def __init__(self, sp, strategy='ring'):
        if int(sp) < 2:
            raise ValueError('sp must be >= 2, got %r' % (sp,))
        if strategy not in ('ring', 'ulysses'):
            raise ValueError("strategy must be 'ring' or 'ulysses', got %r"
                             % (strategy,))
        self.sp = int(sp)
        self.strategy = strategy

    def transpile(self, program=None):
        if program is None:
            program = default_main_program()
        if not any(op.type == 'flash_attention'
                   for blk in program.blocks for op in blk.ops):
            raise ValueError(
                'no fused_attention ops in the program — sequence '
                'parallelism distributes attention; build the model with '
                'fluid.layers.fused_attention (or nets.sdpa)')
        for blk in program.blocks:
            for op in blk.ops:
                if op.type == 'flash_attention':
                    op.attrs['sp_strategy'] = self.strategy
        pipe = getattr(program, '_pipeline_config', None)
        if pipe is not None:
            # PipelineTranspiler already ran: its stage bodies will run
            # sequence-local under this sp mesh — enforce the locality
            # contract (see pipeline_transpiler.validate_sp_sequence_local)
            from .pipeline_transpiler import validate_sp_sequence_local
            lo0, hi0 = pipe['stage0']
            validate_sp_sequence_local(
                program.global_block().ops[lo0:hi0])
        from ._mesh_axes import rebuild_mesh_axes
        base = dict(getattr(program, '_dist_config', None) or {})
        base['sp_size'] = self.sp
        base.setdefault('sync_mode', True)
        base['mesh_axes'] = rebuild_mesh_axes(base)
        program._dist_config = base
        program._dist_mesh = None  # force (re)build with the sp axis
        program._bump_version()
        return self
