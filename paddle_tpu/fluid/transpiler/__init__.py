"""Program transpilers.

Parity: reference python/paddle/fluid/transpiler/ — distribute (pserver/
gRPC), inference, memory optimization. See each module for the TPU-first
redesign.
"""
from .distribute_transpiler import (DistributeTranspiler,
                                    DistributeTranspilerConfig)
from .inference_transpiler import InferenceTranspiler
from .memory_optimization_transpiler import memory_optimize, release_memory
from .pipeline_transpiler import PipelineTranspiler
from .sp_transpiler import SequenceParallelTranspiler
from .tp_transpiler import TensorParallelTranspiler
from .ps_dispatcher import HashName, RoundRobin

__all__ = ['DistributeTranspiler', 'DistributeTranspilerConfig',
           'InferenceTranspiler', 'PipelineTranspiler',
           'SequenceParallelTranspiler', 'TensorParallelTranspiler',
           'memory_optimize', 'release_memory', 'HashName', 'RoundRobin']
