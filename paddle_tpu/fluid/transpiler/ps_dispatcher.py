"""PS dispatchers. Parity: reference transpiler/ps_dispatcher.py (HashName/
RoundRobin decide which pserver owns a var). Kept for API compatibility;
with GSPMD the "dispatch" is the mesh sharding spec."""

__all__ = ['PSDispatcher', 'HashName', 'RoundRobin']


class PSDispatcher(object):
    def __init__(self, pserver_endpoints):
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("Interface has not been implemented.")


class HashName(PSDispatcher):
    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name, len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server = self._eps[self._step]
            eplist.append(server)
            self._step = (self._step + 1) % len(self._eps)
        return eplist
