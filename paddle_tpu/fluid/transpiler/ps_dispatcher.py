"""PS dispatchers — DEPRECATED shims. Parity: reference
transpiler/ps_dispatcher.py (HashName/RoundRobin decide which pserver owns
a var). With the sharded-embedding subsystem (docs/embedding.md) the
"dispatch" decision is static and uniform: a row-sharded table's owner for
id `i` is `i // (vocab / axis_size)` — the mesh sharding spec, consumed by
the all_to_all lookup wire — so these classes only translate old launcher
code: `dispatch()` still round-robins/hashes endpoint strings, and
construction warns with the migration pointer (docs/migration.md)."""
import warnings

__all__ = ['PSDispatcher', 'HashName', 'RoundRobin']


class PSDispatcher(object):
    def __init__(self, pserver_endpoints):
        warnings.warn(
            '%s is deprecated: pserver var dispatch is replaced by mesh '
            "sharding specs — row-shard embedding tables with "
            "ParamAttr(sharding=('dp', None)) + embedding(is_sparse=True, "
            'is_distributed=True) on a Program.set_mesh() program '
            '(docs/embedding.md, migration table in docs/migration.md).'
            % type(self).__name__, DeprecationWarning, stacklevel=2)
        self._eps = list(pserver_endpoints)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError("Interface has not been implemented.")


class HashName(PSDispatcher):
    def _hash_block(self, block_str, total):
        return hash(block_str) % total

    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server_id = self._hash_block(var.name, len(self._eps))
            eplist.append(self._eps[server_id])
        return eplist


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        eplist = []
        for var in varlist:
            server = self._eps[self._step]
            eplist.append(server)
            self._step = (self._step + 1) % len(self._eps)
        return eplist
