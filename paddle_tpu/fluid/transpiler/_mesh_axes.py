"""Shared mesh_axes annotation rebuild for the parallelism transpilers."""

# executor build order (executor.py _dist_place): dp, tp, pp, sp
_CANONICAL = ('dp', 'tp', 'pp', 'sp')


def rebuild_mesh_axes(base):
    """Recompute the mesh_axes annotation from the MERGED axis sizes of a
    _dist_config, in the executor's canonical order, naming the pipeline
    axis by its configured pp_axis (may be custom) rather than the
    literal 'pp'. Every transpiler calls this after updating its own
    *_size so later transpiles never clobber earlier axes."""
    pp_ax = base.get('pp_axis', 'pp')
    return tuple(
        (pp_ax if ax == 'pp' else ax) for ax in _CANONICAL
        if int(base.get(ax + '_size') or 1) > 1)
