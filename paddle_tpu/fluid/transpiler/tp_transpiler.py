"""Tensor-parallel transpiler: Megatron-style layouts from the Fluid API.

TPU-first redesign of intra-layer model parallelism (the reference's only
model-parallel lever was pserver slicing of large vars,
transpiler/distribute_transpiler.py slice_var_up): annotate the program so
the Executor builds a `tp` mesh axis and places every fc/embedding
parameter by `parallel.auto_tp_rules` — the Megatron column/row
alternation derived from the program graph. GSPMD partitions every matmul
touching a sharded weight and inserts the all-reduces on ICI; the rules
decide LAYOUT, never numerics, so tp == single-device exactly.

    transformer(...); opt.minimize(cost)
    fluid.TensorParallelTranspiler(tp=2).transpile(main_program)
    exe.run(main_program, ...)        # fc/embedding weights sharded

Composes with DistributeTranspiler (dp x tp — the classic 2D layout),
SequenceParallelTranspiler (sp rings gather the tp-sharded projections at
the attention boundary), and PipelineTranspiler (dp x pp x tp — the
standard Megatron large-model layout): the pipeline's shard_map is manual
only over dp/pp, so the tp axis stays automatic inside it and GSPMD
partitions each stage's matmuls by the stacked stage params' Megatron
shardings (parallel/pipeline.py).
"""
from ..framework import default_main_program

__all__ = ['TensorParallelTranspiler']


class TensorParallelTranspiler(object):
    def __init__(self, tp):
        if int(tp) < 2:
            raise ValueError('tp must be >= 2, got %r' % (tp,))
        self.tp = int(tp)

    def transpile(self, program=None):
        if program is None:
            program = default_main_program()
        from ...parallel.tp import auto_tp_rules
        if not auto_tp_rules(program):
            raise ValueError(
                'no tensor-parallelizable parameters (fc/embedding) found '
                'in the program')
        from ._mesh_axes import rebuild_mesh_axes
        base = dict(getattr(program, '_dist_config', None) or {})
        base['tp_size'] = self.tp
        base.setdefault('sync_mode', True)
        base['mesh_axes'] = rebuild_mesh_axes(base)
        program._dist_config = base
        program._dist_mesh = None  # force (re)build with the tp axis
        program._bump_version()
        return self
