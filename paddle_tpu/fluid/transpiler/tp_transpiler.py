"""Tensor-parallel transpiler: Megatron-style layouts from the Fluid API.

TPU-first redesign of intra-layer model parallelism (the reference's only
model-parallel lever was pserver slicing of large vars,
transpiler/distribute_transpiler.py slice_var_up): annotate the program so
the Executor builds a `tp` mesh axis and places every fc/embedding
parameter by `parallel.auto_tp_rules` — the Megatron column/row
alternation derived from the program graph. GSPMD partitions every matmul
touching a sharded weight and inserts the all-reduces on ICI; the rules
decide LAYOUT, never numerics, so tp == single-device exactly.

    transformer(...); opt.minimize(cost)
    fluid.TensorParallelTranspiler(tp=2).transpile(main_program)
    exe.run(main_program, ...)        # fc/embedding weights sharded

Composes with DistributeTranspiler (dp x tp — the classic 2D layout) and
SequenceParallelTranspiler (sp rings gather the tp-sharded projections at
the attention boundary). Does NOT compose with PipelineTranspiler: the
pipeline's stacked stage parameters replicate within its shard_map, so the
combination is rejected at transpile time.
"""
from ..framework import default_main_program

__all__ = ['TensorParallelTranspiler']


class TensorParallelTranspiler(object):
    def __init__(self, tp):
        if int(tp) < 2:
            raise ValueError('tp must be >= 2, got %r' % (tp,))
        self.tp = int(tp)

    def transpile(self, program=None):
        if program is None:
            program = default_main_program()
        from ...parallel.tp import auto_tp_rules
        if not auto_tp_rules(program):
            raise ValueError(
                'no tensor-parallelizable parameters (fc/embedding) found '
                'in the program')
        base = dict(getattr(program, '_dist_config', None) or {})
        if int(base.get('pp_size') or 1) > 1 or \
                getattr(program, '_pipeline_config', None) is not None:
            raise ValueError(
                'tensor parallelism does not compose with pipeline '
                'parallelism (stage parameters replicate inside the '
                'pipeline shard_map; see module docstring)')
        base['tp_size'] = self.tp
        base.setdefault('sync_mode', True)
        program._dist_config = base
        program._dist_mesh = None  # force (re)build with the tp axis
        program._bump_version()
        return self
