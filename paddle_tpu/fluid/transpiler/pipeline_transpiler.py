"""Pipeline-parallel transpiler: Fluid Program -> GPipe or circular
(interleaved) schedule.

Program-level entry for parallel/pipeline.py. The user wraps each repeated
stage of the network in `fluid.device_guard('pipe:K')` (K = 0..S-1); ops
appended inside carry `op_device='pipe:K'`. `PipelineTranspiler.transpile`
then

1. checks the stamped ops form one contiguous region of S contiguous,
   structurally IDENTICAL stages (same op-type/attr sequence — the GPipe
   homogeneity requirement: every device runs the same stage function on
   its own weights);
2. aligns the stages op-by-op to classify every stage input as
     - per-stage parameter (different Parameter per stage, same shape)
         -> stacked [S, ...] and sharded over the `pp` mesh axis,
     - shared extra (same var in every stage, produced outside: pad-mask
         biases, a pipelined decoder's encoder output)
         -> replicated to all stages,
     - the flow activation (stage k consumes stage k-1's boundary output)
         -> the microbatched tensor streamed around the ppermute ring;
3. annotates the program (`_pipeline_config` + `_dist_config.pp_size`).

The Executor consumes the annotation: the region runs as ONE
parallel.pipeline_apply call inside the jitted step (scan + ppermute over
the pp mesh axis), and `jax.grad` differentiates straight through it —
scan, ppermute and the emit-gather all have transpose rules, so GPipe's
forward-then-backward microbatch schedule falls out of XLA's scheduling of
the transposed scan rather than being hand-written (the reference has no
pipeline engine at all; its closest precedent is program splitting in
transpiler/distribute_transpiler.py:180-300).

Prologue ops (embedding, masks) and epilogue ops (projection, loss) run
unpipelined on the full batch, replicated over pp — they are cheap relative
to the stage stack, the standard GPipe arrangement.

Untranspiled, the same annotated program runs sequentially (the stamps are
inert attrs) — which is exactly what tests compare against.
"""
from ..framework import Parameter, default_main_program

__all__ = ['PipelineTranspiler']

_STAGE_PREFIX = 'pipe:'

# --------------------------------------------------------------------------
# pp x sp sequence-locality contract.
#
# Under a pp x sp mesh the pipeline region runs inside a shard_map that is
# MANUAL over 'sp': every stage body sees only its sequence shard, and only
# the flash_attention lowering consults ctx.manual_axes to run a per-shard
# ring/ulysses collective. Any other op that mixes or reduces ACROSS
# sequence positions (an unfused q@k^T matmul, sequence_pool, an in-region
# reduce/mean/loss) would silently compute shard-local values and the
# out-spec gather would return wrong numbers. So when both transpilers are
# applied, every stage op must be sequence-LOCAL: it may not combine values
# from different positions of any non-feature dimension, except through
# flash_attention.
#
# `_SP_LOCAL_SAFE` lists op types whose lowerings are positionwise
# (elementwise/activation/layout/feature-dim-only ops). matmul/mul are safe
# only when the Y operand is a Parameter (contraction over feature dims of
# a weight replicated across sp); layer_norm only when it normalizes the
# trailing feature dim. Anything else raises at transpile time — the
# loud-failure contract the pre-round-4 pp+sp rejection used to provide.
# Escape hatch for custom ops the analysis cannot see through: stamp
# `op.attrs['sp_local_safe'] = True`.
#
# Known limit (documented, not detected): the axis checks assume the
# activation keeps a [batch, seq, features...] layout at axis-sensitive ops
# (softmax/layer_norm normalize the LAST dim); a transpose that moves the
# sequence dim into the last position before one of them defeats the check.
_SP_LOCAL_SAFE = frozenset([
    # elementwise binaries / unaries (ops_impl/math_ops.py)
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'logical_and', 'logical_or', 'logical_xor',
    'logical_not', 'clip', 'scale', 'cast', 'sign', 'minus', 'pow',
    'relu', 'prelu', 'label_smooth', 'dropout', 'hard_shrink',
    'thresholded_relu', 'isfinite', 'sum',
    # pure layout / view ops — reindex, never combine positions
    'transpose', 'reshape', 'squeeze', 'unsqueeze', 'flatten',
    # positionwise lookups / constants
    'lookup_table', 'one_hot', 'assign', 'fill_constant',
    'fill_zeros_like', 'shape',
    # normalizes/softmaxes the trailing feature dim only (lowering is
    # axis=-1; layer_norm handled separately via begin_norm_axis)
    'softmax',
    # consults ctx.manual_axes and runs the per-shard ring/ulysses body
    'flash_attention',
])


def _sp_local_safe_types():
    from ..layers.ops import __activations__
    return _SP_LOCAL_SAFE | frozenset(__activations__)


def validate_sp_sequence_local(stage_ops):
    """Raise unless every pipeline-stage op is sequence-local-safe under an
    sp mesh (see the contract comment above). Called by both transpilers
    (whichever runs second sees both configs) and by the Executor as a
    backstop when it builds a pp x sp step."""
    safe = _sp_local_safe_types()
    for op in stage_ops:
        t = op.type
        if t in safe or op.attrs.get('sp_local_safe'):
            continue
        if t in ('mul', 'matmul'):
            ys = op.inputs.get('Y', [])
            if ys and all(isinstance(v, Parameter) or v.persistable
                          for v in ys):
                continue  # x @ W: contraction over feature dims of a
                          # weight replicated across sp
            raise ValueError(
                "pp x sp: stage op '%s' contracts two activations — under "
                "sequence parallelism that mixes sequence positions across "
                "shards (a hand-written attention score matrix, for "
                "example) and would silently compute shard-local values. "
                "Use fluid.layers.fused_attention (the flash_attention "
                "lowering rides the sp ring), or stamp "
                "attrs['sp_local_safe']=True if the contraction provably "
                "never touches the sequence dim." % t)
        if t == 'layer_norm':
            x = op.inputs['X'][0]
            rank = len(x.shape) if x.shape is not None else None
            if rank is not None \
                    and op.attrs.get('begin_norm_axis', 1) == rank - 1:
                continue  # trailing-feature-dim norm is positionwise
            raise ValueError(
                "pp x sp: layer_norm in a pipeline stage must normalize "
                "only the trailing feature dim (begin_norm_axis == rank-1, "
                "got %r for rank %r) — normalizing across the sequence dim "
                "would mix positions that live on different sp shards."
                % (op.attrs.get('begin_norm_axis', 1), rank))
        raise ValueError(
            "pp x sp: op '%s' inside the pipeline region is not known to "
            "be sequence-local. Under an sp mesh every stage body runs on "
            "a sequence SHARD; ops that mix or reduce across sequence "
            "positions (sequence_*, reduce_*, pooling, conv over seq, "
            "in-region losses) would silently produce shard-local values. "
            "Move the op outside the device_guard('pipe:K') region, or — "
            "if it provably never combines different sequence positions — "
            "stamp attrs['sp_local_safe']=True on it." % t)


def _stage_of(op):
    dev = op.attrs.get('op_device')
    if isinstance(dev, str) and dev.startswith(_STAGE_PREFIX):
        return int(dev[len(_STAGE_PREFIX):])
    return None


def _attrs_key(op):
    return {k: v for k, v in op.attrs.items()
            if k not in ('op_device', 'op_role')}


class PipelineTranspiler(object):
    """Turn device_guard('pipe:K') stage annotations into a pipeline config.

        t = PipelineTranspiler(n_micro=4)              # GPipe
        t = PipelineTranspiler(n_micro=4, n_virtual=2) # circular schedule
        t.transpile(main_program)          # annotates the program
        exe.run(main_program, ...)         # region runs pipelined

    n_micro must divide the batch size. The pp mesh axis size equals the
    number of annotated stages divided by n_virtual: with n_virtual > 1
    each device holds n_virtual chunks and every microbatch rides the ring
    n_virtual times (the Megatron/praxis interleaved loop placement),
    shrinking the fill/drain bubble by n_virtual at the cost of n_micro
    having to be a multiple of the device count.
    """

    def __init__(self, n_micro=4, axis='pp', n_virtual=1):
        self.n_micro = int(n_micro)
        self.axis = axis
        # circular (interleaved) schedule: n_virtual chunks per device,
        # each microbatch rides the ring n_virtual times — the fill/drain
        # bubble shrinks by n_virtual (see parallel/pipeline.py docstring)
        self.n_virtual = int(n_virtual)
        if self.n_virtual < 1:
            raise ValueError('n_virtual must be >= 1, got %d'
                             % self.n_virtual)

    def transpile(self, program=None):
        if program is None:
            program = default_main_program()
        # tp composes via GSPMD (the shard_map is manual only over
        # dp/pp/sp — GSPMD partitions tp inside the stages); sp composes
        # manually: pipeline_apply shards the activation's sequence dim
        # over 'sp' and the attention lowering rides the ring per shard.
        base = dict(getattr(program, '_dist_config', None) or {})
        block = program.global_block()
        ops = block.ops

        stamped = [(i, _stage_of(op)) for i, op in enumerate(ops)
                   if _stage_of(op) is not None]
        if not stamped:
            raise ValueError(
                'no device_guard("pipe:K") stages found in the program')
        lo, hi = stamped[0][0], stamped[-1][0] + 1
        stages = sorted({s for _, s in stamped})
        S = len(stages)
        if stages != list(range(S)) or S < 2:
            raise ValueError(
                'pipeline stages must be 0..S-1 with S>=2, got %r' % stages)

        # contiguity: the region is gap-free and stages appear in order,
        # each as one contiguous run
        segs = {}
        prev_stage = None
        for i in range(lo, hi):
            s = _stage_of(ops[i])
            if s is None:
                raise ValueError(
                    'op %r at index %d sits inside the pipeline region but '
                    'has no pipe stage annotation' % (ops[i].type, i))
            if s != prev_stage:
                if s in segs:
                    raise ValueError('stage %d is not contiguous' % s)
                if prev_stage is not None and s != prev_stage + 1:
                    raise ValueError(
                        'stages must appear in increasing order; got %d '
                        'after %d' % (s, prev_stage))
                segs[s] = [i, i + 1]
                prev_stage = s
            else:
                segs[s][1] = i + 1

        seg_ops = {s: ops[a:b] for s, (a, b) in segs.items()}
        n0 = len(seg_ops[0])
        for s in range(1, S):
            if len(seg_ops[s]) != n0:
                raise ValueError(
                    'stage %d has %d ops, stage 0 has %d — stages must be '
                    'structurally identical' % (s, len(seg_ops[s]), n0))
            for j, (a, b) in enumerate(zip(seg_ops[0], seg_ops[s])):
                if a.type != b.type:
                    raise ValueError(
                        'op %d differs: stage 0 %r vs stage %d %r'
                        % (j, a.type, s, b.type))
                if _attrs_key(a) != _attrs_key(b):
                    raise ValueError(
                        'attrs of op %d (%s) differ between stage 0 and '
                        'stage %d — stages must be structurally identical'
                        % (j, a.type, s))
                # slot SETS must match exactly: the executor replays stage
                # 0's op list for every stage, so an optional input/output
                # present only in a later stage would be silently dropped
                if sorted(a.inputs) != sorted(b.inputs):
                    raise ValueError(
                        'input slots of op %d (%s) differ between stage 0 '
                        '%r and stage %d %r'
                        % (j, a.type, sorted(a.inputs), s, sorted(b.inputs)))
                if sorted(a.outputs) != sorted(b.outputs):
                    raise ValueError(
                        'output slots of op %d (%s) differ between stage 0 '
                        '%r and stage %d %r'
                        % (j, a.type, sorted(a.outputs), s,
                           sorted(b.outputs)))

        # ------------------------------------------------------------------
        # classify inputs by aligning each adjacent stage pair
        produced_in = [set() for _ in range(S)]
        for s in range(S):
            for op in seg_ops[s]:
                produced_in[s].update(op.output_arg_names)

        param_names = [[] for _ in range(S)]   # [S][j] aligned param names
        extra_names = []
        boundary = [None] * S   # boundary[k] = stage k's flow output var
        input_var = None

        def classify_pair(k):
            """Align stage k-1 and stage k; fill param/extra/flow info."""
            nonlocal input_var
            flow_pairs = set()
            for j in range(n0):
                a, b = seg_ops[k - 1][j], seg_ops[k][j]
                for slot in a.inputs:
                    va_l, vb_l = a.inputs[slot], b.inputs.get(slot, [])
                    if len(va_l) != len(vb_l):
                        raise ValueError(
                            'op %d (%s) slot %r arity differs between '
                            'stages %d and %d' % (j, a.type, slot, k - 1, k))
                    for va, vb in zip(va_l, vb_l):
                        if va.name == vb.name:
                            if (va.name in produced_in[k - 1]
                                    or va.name in produced_in[k]):
                                raise ValueError(
                                    'var %r is produced inside one stage '
                                    'but read by another — stages may only '
                                    'communicate through the single flow '
                                    'activation' % va.name)
                            # shared external tensor (mask bias, tied
                            # weight, pipelined decoder's encoder output):
                            # replicated to every stage
                            if va.name not in extra_names:
                                extra_names.append(va.name)
                        elif (isinstance(va, Parameter)
                              and isinstance(vb, Parameter)):
                            if va.shape != vb.shape or va.dtype != vb.dtype:
                                raise ValueError(
                                    'aligned parameters %r/%r differ in '
                                    'shape/dtype' % (va.name, vb.name))
                            if k == 1:
                                if va.name not in param_names[0]:
                                    param_names[0].append(va.name)
                                    param_names[1].append(vb.name)
                            else:
                                # consistency with the 0/1 alignment
                                idx = param_names[k - 1].index(va.name)
                                while len(param_names[k]) <= idx:
                                    param_names[k].append(None)
                                param_names[k][idx] = vb.name
                        elif (va.name in produced_in[k - 1]
                              and vb.name in produced_in[k]):
                            continue  # internal dataflow, aligned by index
                        else:
                            # the flow slot: stage k-1 reads its input,
                            # stage k reads stage k-1's boundary output
                            flow_pairs.add((va.name, vb.name))
            if len(flow_pairs) != 1:
                raise ValueError(
                    'expected exactly one activation flowing between '
                    'stages %d and %d, found %r — mark shared tensors by '
                    'using the SAME variable in every stage'
                    % (k - 1, k, sorted(flow_pairs)))
            src, dst = flow_pairs.pop()
            if k == 1:
                if src in region_produced_any():
                    raise ValueError(
                        'stage 0 input %r must come from before the '
                        'pipeline region' % src)
                input_var = src
            elif src != boundary[k - 2]:
                raise ValueError(
                    'flow chain broken: stage %d reads %r but stage %d '
                    'emits %r' % (k - 1, src, k - 2, boundary[k - 2]))
            if dst not in produced_in[k - 1]:
                raise ValueError(
                    'flow var %r is not produced by stage %d'
                    % (dst, k - 1))
            boundary[k - 1] = dst
            return src, dst

        def region_produced_any():
            return set().union(*produced_in)

        for k in range(1, S):
            classify_pair(k)
        nparam = len(param_names[0])
        for k in range(S):
            if len(param_names[k]) != nparam or None in param_names[k]:
                raise ValueError(
                    'parameter alignment incomplete for stage %d '
                    '(%r vs stage 0 %r)' % (k, param_names[k],
                                            param_names[0]))

        # last stage's flow output: produced by the op aligned with the
        # one that produces boundary[0] in stage 0
        def producer_index(k, name):
            for j, op in enumerate(seg_ops[k]):
                for slot, vs in op.outputs.items():
                    for pos, v in enumerate(vs):
                        if v.name == name:
                            return j, slot, pos
            raise ValueError('%r not produced by stage %d' % (name, k))

        jb, slot_b, pos_b = producer_index(0, boundary[0])
        out_op = seg_ops[S - 1][jb]
        output_var = out_op.outputs[slot_b][pos_b].name
        boundary[S - 1] = output_var

        # escape check: nothing but the final boundary may leave the region
        region_produced = set().union(*produced_in)
        consumed_after = set()
        for op in ops[hi:]:
            consumed_after.update(op.input_arg_names)
        leaked = (region_produced & consumed_after) - {output_var}
        if leaked:
            raise ValueError(
                'vars %r produced inside the pipeline region are consumed '
                'after it; only the final stage output %r may escape'
                % (sorted(leaked), output_var))

        in_v = block._var_recursive(input_var)
        out_v = block._var_recursive(output_var)
        if (in_v.shape is not None and out_v.shape is not None
                and tuple(in_v.shape) != tuple(out_v.shape)):
            raise ValueError(
                'pipeline stages must preserve the activation shape: input '
                '%r %r vs output %r %r' % (input_var, in_v.shape,
                                           output_var, out_v.shape))
        if (in_v.dtype is not None and out_v.dtype is not None
                and in_v.dtype != out_v.dtype):
            # catch AMP-boundary mismatches here, not as an opaque
            # lax.scan carry error at trace time
            raise ValueError(
                'pipeline stages must preserve the activation dtype: input '
                '%r %r vs output %r %r' % (input_var, in_v.dtype,
                                           output_var, out_v.dtype))

        # batch-aligned extras (leading dynamic dim: pad-mask biases, a
        # pipelined decoder's encoder output) are streamed per-microbatch;
        # static-shape extras (tied weights, tables) replicate whole
        stream, static = [], []
        for n in extra_names:
            v = block._var_recursive(n)
            if v.shape is not None and len(v.shape) and v.shape[0] == -1:
                stream.append(n)
            else:
                static.append(n)

        if S % self.n_virtual or S // self.n_virtual < 2:
            raise ValueError(
                'n_virtual=%d must divide the %d stamped stages with '
                'stages/n_virtual >= 2 (that quotient is the pp mesh axis '
                'size — devices each hold n_virtual chunks)'
                % (self.n_virtual, S))
        if self.n_virtual > 1 and self.n_micro % (S // self.n_virtual):
            # statically knowable: fail at transpile time, not inside jit
            raise ValueError(
                'circular pipeline (n_virtual=%d) injects microbatches in '
                'rounds of the device count %d; n_micro=%d is not a '
                'multiple' % (self.n_virtual, S // self.n_virtual,
                              self.n_micro))

        if base.get('sp_size'):
            # SequenceParallelTranspiler already ran: stage bodies will run
            # sequence-local inside the manual shard_map — enforce the
            # locality contract now, loudly
            validate_sp_sequence_local(seg_ops[0])

        program._pipeline_config = {
            'axis': self.axis,
            'n_micro': self.n_micro,
            'n_virtual': self.n_virtual,
            'n_stages': S,
            'region': (lo, hi),
            'stage0': tuple(segs[0]),
            'param_names': param_names,
            'input_var': input_var,
            'boundary0': boundary[0],
            'output_var': output_var,
            'extra_stream_names': stream,
            'extra_names': static,
        }
        from ._mesh_axes import rebuild_mesh_axes
        base['pp_size'] = S // self.n_virtual
        base['pp_axis'] = self.axis
        base.setdefault('sync_mode', True)
        base['mesh_axes'] = rebuild_mesh_axes(base)
        program._dist_config = base
        program._dist_mesh = None  # force (re)build with the pp axis
        program._bump_version()
        return self
