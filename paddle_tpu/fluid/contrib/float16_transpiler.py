"""Half-precision inference transpiler.

Parity: reference paddle/contrib/float16/float16_transpiler.py, which
rewrites an inference ProgramDesc for fp16 — casting weights in the scope,
patching var dtypes, and appending cast ops at the feed/fetch boundary so
users keep feeding/fetching float32.

TPU-first redesign: the half dtype is bfloat16 (same exponent range as
fp32 — no scaling concerns, native MXU speed) and XLA lowerings are
dtype-polymorphic, so no kernel re-selection or cast-op surgery on the op
list is needed. transpile():

1. casts every floating persistable parameter in the scope to bf16
   (halves HBM + doubles effective MXU throughput for serving),
2. patches the matching Parameter dtypes in the program,
3. enables the program's amp mode so remaining fp32 inputs (feeds) are
   cast at matmul/conv boundaries inside the fused step, and
4. flags the program so Executor.run returns float32 fetches (the
   reference's fetch-side cast ops) — feeds stay float32 on the user side.
"""
import numpy as np

from ..framework import Program

__all__ = ['Float16Transpiler', 'BF16Transpiler']


class Float16Transpiler(object):
    #: the TPU half dtype; fp16 is accepted for API compat but bf16 is
    #: what the MXU natively runs and needs no loss-scale hygiene
    target_dtype = 'bfloat16'

    def transpile(self, program, place=None, scope=None):
        """Convert an inference program + its scope weights to half
        precision in place. `place` is accepted for reference-signature
        compat (dtype choice does not depend on it on TPU)."""
        import jax.numpy as jnp
        from .. import amp
        from ..executor import global_scope

        if not isinstance(program, Program):
            raise TypeError('program should be a Program, got %r'
                            % type(program))
        scope = scope if scope is not None else global_scope()
        half = jnp.bfloat16

        converted = []
        params = {v.name: v for v in program.list_vars()
                  if v.persistable and str(v.dtype) in
                  ('float32', 'float64')}
        for name, var in params.items():
            val = scope._chain_get(name)
            if val is None or not hasattr(val, 'dtype'):
                continue
            if np.issubdtype(np.asarray(val).dtype, np.floating):
                scope._chain_set(name, jnp.asarray(val).astype(half))
                var.dtype = 'bfloat16'
                converted.append(name)

        amp.decorate_program(program)      # cast feeds at MXU boundaries
        program._fetch_f32 = True          # fetch-side cast back to fp32
        program._bump_version()
        return converted


# the honest TPU name; Float16Transpiler kept for ported scripts
BF16Transpiler = Float16Transpiler
