from . import beam_search_decoder
from .beam_search_decoder import *

__all__ = beam_search_decoder.__all__
