"""Layered RNN decoder helper library: InitState / StateCell /
TrainingDecoder / BeamSearchDecoder.

Parity: reference python/paddle/fluid/contrib/decoder/beam_search_decoder.py
(same classes, same user contract — see the reference's
tests/test_beam_search_decoder.py flow). TPU-first redesign of the
internals:

- TrainingDecoder rides the masked lax.scan DynamicRNN (one fused scan per
  decode, static shapes) instead of the reference's length-sorted
  DynamicRNNOp with per-step batch shrinking.
- BeamSearchDecoder runs a fixed-trip While loop (lax.while_loop) over a
  dense [batch*beam] layout with explicit parent pointers, instead of the
  reference's LoD-shrinking arrays + early-stop is_empty. States are
  loop-carried vars; `need_reorder` states are re-gathered by the
  beam_search op's global parent rows each step. The decoded lineage is
  backtraced on-device by beam_search_decode (one lax.scan), not by a host
  walk of LoDTensorArrays.
"""
import numpy as np

from ... import framework
from ...layers import control_flow, nn, ops, tensor
from ...layer_helper import LayerHelper

__all__ = ['InitState', 'StateCell', 'TrainingDecoder', 'BeamSearchDecoder']


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState(object):
    """Initial value of a decoder state: either an existing Variable
    (e.g. the encoder's last step) or a (shape, value) constant built
    against a boot var's batch dim. `need_reorder` marks states that must
    follow beam lineage during search (hidden states yes, static context
    usually no)."""

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype='float32'):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                'init_boot must be provided to infer the init state shape')
        else:
            self._init = tensor.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState(object):
    """Training-time adapter: the state lives as a DynamicRNN memory."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _LoopState(object):
    """Beam-search adapter: the state is a loop-carried var on the decode
    While loop, pre-expanded to the dense [batch*beam] layout."""

    def __init__(self, state_name, decoder_obj, init_state):
        self._state_name = state_name
        self._decoder_obj = decoder_obj
        self._need_reorder = init_state.need_reorder
        # built OUTSIDE the While block: [batch, ...] -> [batch*beam, ...]
        self._var = tensor.assign(
            decoder_obj._expand_to_beam(init_state.value))

    def get_state(self):
        return self._var

    def update_state(self, state):
        if self._need_reorder:
            state = nn.gather(state, self._decoder_obj._parent_idx)
        tensor.assign(state, output=self._var)


class StateCell(object):
    """Holds decoder states + per-step inputs and a user-registered updater
    computing new states from them; adapts onto whichever decoder
    (training scan or beam-search loop) it is used inside."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper('state_cell', name=name)
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError('state must be an InitState object.')
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = inputs            # name -> Variable or None placeholder
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}         # state_name -> {id(decoder): adapter}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError('StateCell has already entered a decoder.')
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError('StateCell not in decoding.')
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError('Inconsistent decoder object in StateCell.')
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        """Lazily adapt each state onto the current decoder the first time
        it is touched inside the decoder's block."""
        if not self._in_decoder:
            raise ValueError('StateCell must be enclosed by a decoder.')
        if self._switched_decoder:
            raise ValueError('StateCell already switched to this decoder.')
        for state_name in self._state_names:
            if state_name not in self._states_holder:
                self._states_holder[state_name] = {}
            init_state = self._cur_states[state_name]
            if not isinstance(init_state, InitState):
                raise ValueError('Decoder switch requires an InitState; '
                                 'state %r was already consumed' % state_name)
            decoder_obj = self._cur_decoder_obj
            if decoder_obj.type == _DecoderType.TRAINING:
                adapter = _MemoryState(state_name, decoder_obj.dynamic_rnn,
                                       init_state)
            elif decoder_obj.type == _DecoderType.BEAM_SEARCH:
                adapter = _LoopState(state_name, decoder_obj, init_state)
            else:
                raise ValueError('Unknown decoder type %s' % decoder_obj.type)
            self._states_holder[state_name][id(decoder_obj)] = adapter
            self._cur_states[state_name] = adapter.get_state()
        self._switched_decoder = True

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError('Unknown state %s.' % state_name)
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError('Invalid input %s.' % input_name)
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._cur_states:
            raise ValueError('Unknown state %s.' % state_name)
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater
        return updater

    def compute_state(self, inputs):
        """Run the registered updater with this step's inputs filled in."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError('Unknown input %s. Cannot compute states.'
                                 % input_name)
            self._inputs[input_name] = input_value
        if self._state_updater is None:
            raise ValueError('No state updater registered; decorate one '
                             'with @state_cell.state_updater')
        self._state_updater(self)

    def update_states(self):
        """Push the computed states back into the decoder's carriers
        (RNN memories or loop vars)."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for state_name, decoder_state in self._states_holder.items():
            if id(self._cur_decoder_obj) not in decoder_state:
                raise ValueError('Unknown decoder object; state %s leaked '
                                 'from another decoder' % state_name)
            decoder_state[id(self._cur_decoder_obj)].update_state(
                self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder(object):
    """Teacher-forced decoding over the gold target sequence; one fused
    lax.scan via DynamicRNN. Usage mirrors the reference::

        decoder = TrainingDecoder(state_cell)
        with decoder.block():
            w = decoder.step_input(trg_embedding)
            decoder.state_cell.compute_state(inputs={'x': w})
            score = layers.fc(decoder.state_cell.get_state('h'), ...)
            decoder.state_cell.update_states()
            decoder.output(score)
        out = decoder()
    """
    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper('training_decoder', name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = control_flow.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _block():
            if self._status != TrainingDecoder.BEFORE_DECODER:
                raise ValueError('decoder.block() can only be invoked once')
            self._status = TrainingDecoder.IN_DECODER
            with self._dynamic_rnn.block():
                yield
            self._status = TrainingDecoder.AFTER_DECODER
            self._state_cell._leave_decoder(self)
        return _block()

    @property
    def state_cell(self):
        self._assert_in_decoder_block('state_cell')
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def step_input(self, x):
        self._assert_in_decoder_block('step_input')
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block('static_input')
        return self._dynamic_rnn.static_input(x)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError('Output of TrainingDecoder can only be visited '
                             'outside the block.')
        return self._dynamic_rnn(*args, **kwargs)

    def output(self, *outputs):
        self._assert_in_decoder_block('output')
        self._dynamic_rnn.output(*outputs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError('%s should be invoked inside block of '
                             'TrainingDecoder object.' % method)


class BeamSearchDecoder(object):
    """Beam-search generation driven by the same StateCell used in
    training. `decode()` builds the whole search loop (embedding of the
    previous tokens, state update, vocab projection, joint top-k beam step,
    lineage bookkeeping); `decoder()` afterwards returns
    (translation_ids [batch, beam, max_len], translation_scores
    [batch, beam]). Dense TPU contract: every step runs all batch*beam rows;
    finished beams are frozen by the beam_search op, and the loop always
    runs max_len trips (bounded, compilable — no dynamic early exit)."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict={}, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=2, end_id=1, name=None):
        self._helper = LayerHelper('beam_search_decoder', name=name)
        self._counter = None
        self._status = BeamSearchDecoder.BEFORE_DECODER
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._type = _DecoderType.BEAM_SEARCH
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = input_var_dict
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._parent_idx = None
        self._translation_ids = None
        self._translation_scores = None

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        return self._state_cell

    def _expand_to_beam(self, x):
        """[batch, ...] -> [batch*beam, ...] with each source's rows
        contiguous (row b becomes rows b*beam .. b*beam+beam-1)."""
        trailing = list(x.shape[1:])
        x3 = nn.reshape(x, shape=[-1, 1] + trailing)
        tiled = nn.expand(x3, [1, self._beam_size] + [1] * len(trailing))
        return nn.reshape(tiled, shape=[-1] + trailing)

    def decode(self):
        """Build the full decode loop. Equivalent of the reference's
        decode() (beam_search_decoder.py:653) minus the LoD machinery."""
        if self._status != BeamSearchDecoder.BEFORE_DECODER:
            raise ValueError('decode() can only be called once')
        self._status = BeamSearchDecoder.IN_DECODER
        state_cell = self._state_cell
        beam = self._beam_size

        # ---- outside the loop: dense beam expansion --------------------
        # init_ids/init_scores arrive as lod-2 vars in the reference API;
        # dense layout is one (token, score) per source: flatten first
        prev_ids = tensor.assign(self._expand_to_beam(
            nn.reshape(self._init_ids, shape=[-1, 1])))
        # non-first beams start at -1e9 so step 1 doesn't duplicate beams
        sc3 = self._expand_to_beam(
            nn.reshape(self._init_scores, shape=[-1, 1]))
        bias = np.full((beam, 1), -1e9, dtype=np.float32)
        bias[0, 0] = 0.0
        beam_bias = tensor.assign(bias)                      # [beam, 1]
        sc3 = nn.reshape(sc3, shape=[-1, beam, 1])
        sc3 = ops.elementwise_add(x=sc3, y=beam_bias, axis=1)
        prev_scores = tensor.assign(nn.reshape(sc3, shape=[-1, 1]))

        # adapt states onto this decoder NOW so their beam expansion ops
        # land outside the loop (loop-carried init, not per-trip re-init)
        if not state_cell._switched_decoder:
            state_cell._switch_decoder()
        # static per-source context: expand once, outside the loop
        expanded_inputs = {}
        for init_var_name, init_var in self._input_var_dict.items():
            if init_var_name not in state_cell._inputs:
                raise ValueError('Variable %s not found in StateCell inputs'
                                 % init_var_name)
            expanded_inputs[init_var_name] = self._expand_to_beam(init_var)

        ids_array = control_flow.create_array('int64',
                                              capacity=self._max_len)
        scores_array = control_flow.create_array('float32',
                                                 capacity=self._max_len)
        parents_array = control_flow.create_array('int64',
                                                  capacity=self._max_len)

        counter = tensor.zeros(shape=[1], dtype='int64')
        self._counter = counter
        # seed slot 0 so the loop carries have static shapes; the first
        # trip's write at counter==0 overwrites these placeholders
        control_flow.array_write(prev_ids, counter, ids_array)
        control_flow.array_write(prev_scores, counter, scores_array)
        control_flow.array_write(prev_ids, counter, parents_array)
        max_len = tensor.fill_constant(shape=[1], dtype='int64',
                                       value=self._max_len)
        cond = control_flow.less_than(x=counter, y=max_len)
        while_op = control_flow.While(cond=cond)

        with while_op.block():
            prev_ids_embedding = nn.embedding(
                input=prev_ids,
                size=[self._target_dict_dim, self._word_dim],
                dtype='float32', is_sparse=self._sparse_emb)

            feed_dict = dict(expanded_inputs)
            for input_name in state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_ids_embedding

            state_cell.compute_state(inputs=feed_dict)
            current_state = state_cell.out_state()
            scores = nn.fc(input=current_state,
                           size=self._target_dict_dim, act='softmax')
            topk_scores, topk_indices = nn.topk(scores, k=self._topk_size)
            accu_scores = ops.elementwise_add(
                x=nn.log(topk_scores),
                y=nn.reshape(prev_scores, shape=[-1]), axis=0)
            selected_ids, selected_scores, parent_idx = nn.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                self._beam_size, self._end_id, return_parent_idx=True)
            self._parent_idx = parent_idx

            control_flow.array_write(selected_ids, counter, ids_array)
            control_flow.array_write(selected_scores, counter, scores_array)
            control_flow.array_write(nn.reshape(parent_idx, shape=[-1, 1]),
                                     counter, parents_array)

            state_cell.update_states()
            tensor.assign(selected_ids, output=prev_ids)
            tensor.assign(selected_scores, output=prev_scores)
            control_flow.increment(x=counter, value=1, in_place=True)
            control_flow.less_than(x=counter, y=max_len, cond=cond)

        # ---- after the loop: stack arrays + backtrace on device --------
        stacked_ids = nn.reshape(_array_stack(ids_array),
                                 shape=[self._max_len, -1, beam])
        stacked_scores = nn.reshape(_array_stack(scores_array),
                                    shape=[self._max_len, -1, beam])
        stacked_parents = nn.reshape(_array_stack(parents_array),
                                     shape=[self._max_len, -1, beam])
        self._translation_ids, self._translation_scores = \
            nn.beam_search_decode(stacked_ids, stacked_scores,
                                  beam_size=beam, end_id=self._end_id,
                                  parents=stacked_parents)

        self._status = BeamSearchDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    def read_array(self, init, is_ids=False, is_scores=False):
        """API-parity helper (reference read_array): expand `init` to the
        beam layout and return a loop-carried var seeded with it."""
        self._assert_in_decoder_block('read_array')
        if is_ids and is_scores:
            raise ValueError('Shouldn\'t mark current array be ids array and '
                             'scores array at the same time.')
        return tensor.assign(self._expand_to_beam(init))

    def update_array(self, array, value):
        """API-parity helper (reference update_array): write this step's
        value back into the loop-carried var."""
        self._assert_in_decoder_block('update_array')
        tensor.assign(value, output=array)

    def __call__(self):
        if self._status != BeamSearchDecoder.AFTER_DECODER:
            raise ValueError('Output of BeamSearchDecoder object can only be '
                             'visited outside the block.')
        return self._translation_ids, self._translation_scores

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_DECODER:
            raise ValueError('%s should be invoked inside block of '
                             'BeamSearchDecoder object.' % method)


def _array_stack(array):
    """Append the array_stack op: LoDTensorArray -> [capacity, ...] tensor."""
    helper = LayerHelper('array_stack')
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(type='array_stack', inputs={'Array': [array]},
                     outputs={'Out': [out]}, infer_shape=False)
    return out
