"""Estimate a Program's device-memory footprint before running it.

Parity: reference python/paddle/fluid/contrib/memory_usage_calc.py
(memory_usage(program, batch_size) -> (lower, upper, unit)). On TPU this
estimates the HBM working set from the Program's static var shapes — the
useful pre-flight check before committing to a batch size, since XLA
allocates the whole arena at compile time. The reference sums vars of the
global block only; so do we (intermediate fusion temporaries are XLA's
concern and typically net out below the var-sum on TPU because of fusion,
hence the same 5-10% headroom band)."""
from ..framework import Program

__all__ = ['memory_usage']

DEBUG = False

dtype_to_size = {
    'float16': 2, 'bfloat16': 2, 'float32': 4, 'float64': 8,
    'int8': 1, 'int16': 2, 'int32': 4, 'int64': 8, 'uint8': 1, 'bool': 1,
}


def memory_usage(program, batch_size):
    """Return (lower_bound, upper_bound, unit) estimated memory usage of
    running `program` with the given batch size substituted for -1 dims."""
    if not isinstance(program, Program):
        raise TypeError(
            "Calculating Memory Usage requires Program as its Parameter. "
            "But you passed in %s" % type(program))
    if batch_size <= 0:
        raise ValueError("The batch size need to be positive.")

    total_memory = 0.0
    for var in program.global_block().vars.values():
        shape = var.shape
        if shape is None:
            continue
        data_count = 1
        for x in shape:
            data_count *= batch_size if x == -1 else x
        var_memory = data_count * dtype_to_size.get(str(var.dtype), 4)
        if DEBUG:
            print("%s memory usage: %d" % (var.name, var_memory))
        total_memory += var_memory

    unit_str = "B"
    if total_memory > 1024:
        total_memory /= 1024
        unit_str = "KB"
        if total_memory > 1024:
            total_memory /= 1024
            unit_str = "MB"

    # headroom band for runtime temporaries (5% - 10%)
    return total_memory * 1.05, total_memory * 1.1, unit_str
