"""fluid.contrib — parity with reference python/paddle/fluid/contrib
(memory_usage_calc + decoder helper library)."""
from . import decoder
from . import memory_usage_calc
from .memory_usage_calc import memory_usage
from . import float16_transpiler
from .float16_transpiler import Float16Transpiler, BF16Transpiler

__all__ = ['decoder', 'memory_usage_calc', 'memory_usage',
           'float16_transpiler', 'Float16Transpiler', 'BF16Transpiler']
