"""fluid.contrib — parity with reference python/paddle/fluid/contrib
(memory_usage_calc + decoder helper library)."""
from . import decoder
from . import memory_usage_calc
from .memory_usage_calc import memory_usage

__all__ = ['decoder', 'memory_usage_calc', 'memory_usage']
