"""Parameter initializers: append init ops to the startup program.

Parity: reference python/paddle/fluid/initializer.py (Constant/Uniform/
Normal/Xavier/MSRA/Bilinear). Each __call__ appends one op to the var's
block (normally the startup program); the ops lower to jax.random on device.
"""
import contextlib

import numpy as np

__all__ = [
    'Constant', 'Uniform', 'Normal', 'Xavier', 'Bilinear', 'MSRA',
    'force_init_on_cpu', 'init_on_cpu', 'ConstantInitializer',
    'UniformInitializer', 'NormalInitializer', 'XavierInitializer',
    'BilinearInitializer', 'MSRAInitializer', 'TruncatedNormal',
]

_force_init_on_cpu_ = False


def force_init_on_cpu():
    return _force_init_on_cpu_


@contextlib.contextmanager
def init_on_cpu():
    global _force_init_on_cpu_
    prev = _force_init_on_cpu_
    _force_init_on_cpu_ = True
    try:
        yield
    finally:
        _force_init_on_cpu_ = prev


class Initializer(object):
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self._value = value

    def __call__(self, var, block):
        return block.append_op(
            type='fill_constant', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'value': float(self._value)},
            infer_shape=False)


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high, self._seed = low, high, seed

    def __call__(self, var, block):
        return block.append_op(
            type='uniform_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'min': self._low, 'max': self._high, 'seed': self._seed},
            infer_shape=False)


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='gaussian_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self._mean, 'std': self._std, 'seed': self._seed},
            infer_shape=False)


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self._mean, self._std, self._seed = loc, scale, seed

    def __call__(self, var, block):
        return block.append_op(
            type='truncated_gaussian_random', outputs={'Out': var},
            attrs={'shape': list(var.shape), 'dtype': var.dtype,
                   'mean': self._mean, 'std': self._std, 'seed': self._seed},
            infer_shape=False)


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return (shape[0] if shape else 1), (shape[0] if shape else 1)
    fan_in = int(np.prod(shape[1:]))
    fan_out = int(shape[0] * np.prod(shape[2:])) if len(shape) > 2 else shape[1]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """reference initializer.py XavierInitializer (Glorot)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self._uniform, self._fan_in, self._fan_out, self._seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        if self._uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class MSRAInitializer(Initializer):
    """reference initializer.py MSRAInitializer (He)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self._uniform, self._fan_in, self._seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self._fan_in if self._fan_in is not None else fi
        if self._uniform:
            limit = float(np.sqrt(6.0 / fi))
            return UniformInitializer(-limit, limit, self._seed)(var, block)
        std = float(np.sqrt(2.0 / fi))
        return NormalInitializer(0.0, std, self._seed)(var, block)


class BilinearInitializer(Initializer):
    """Bilinear upsample kernel init for conv_transpose
    (reference initializer.py BilinearInitializer)."""

    def __call__(self, var, block):
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("bilinear init needs a 4-D filter")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype='float32')
        size = int(np.prod(shape))
        idx = np.arange(size)
        x = idx % shape[3]
        y = (idx // shape[3]) % shape[2]
        w = ((1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c)))
        weight.flat[idx] = w
        return block.append_op(
            type='assign_value', outputs={'Out': var},
            attrs={'shape': list(shape), 'dtype': var.dtype,
                   'values': weight.reshape(-1).tolist()},
            infer_shape=False)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type='assign_value', outputs={'Out': var},
            attrs={'shape': list(self._value.shape), 'dtype': str(self._value.dtype),
                   'values': self._value.reshape(-1).tolist()},
            infer_shape=False)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer
