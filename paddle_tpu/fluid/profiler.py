"""Profiler. Parity: reference python/paddle/fluid/profiler.py.

The reference wraps CUDA profiler + its own C++ event tracer; here the
device timeline comes from jax.profiler (XLA trace viewable in TensorBoard/
Perfetto) and the summary table from host wall-clock around Executor.run.
"""
import contextlib
import os
import time

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
           'stop_profiler']

_state = {'active': False, 'trace_dir': None, 't0': None}


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Compat shim (no CUDA on TPU): behaves like profiler()."""
    with profiler('All', 'default', output_file):
        yield


def start_profiler(state='All', trace_dir=None):
    if _state['active']:
        return
    import jax
    trace_dir = trace_dir or os.environ.get('PADDLE_TPU_TRACE_DIR',
                                            '/tmp/paddle_tpu_trace')
    try:
        jax.profiler.start_trace(trace_dir)
        _state['trace_dir'] = trace_dir
    except Exception:
        _state['trace_dir'] = None
    _state['active'] = True
    _state['t0'] = time.time()


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    if not _state['active']:
        return
    import jax
    if _state['trace_dir'] is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    wall = time.time() - _state['t0']
    report = ("------------- paddle_tpu profiler -------------\n"
              "wall time: %.3fs\nXLA trace: %s\n" %
              (wall, _state['trace_dir'] or '(trace unavailable)'))
    try:
        with open(profile_path, 'w') as f:
            f.write(report)
    except Exception:
        pass
    print(report)
    _state['active'] = False


def reset_profiler():
    _state['t0'] = time.time()


@contextlib.contextmanager
def profiler(state='All', sorted_key='default', profile_path='/tmp/profile'):
    start_profiler(state)
    yield
    stop_profiler(sorted_key, profile_path)
