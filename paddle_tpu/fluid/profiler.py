"""Profiler. Parity: reference python/paddle/fluid/profiler.py.

The reference wraps the CUDA profiler + its own C++ event tracer and prints
a sorted per-op event table (reference profiler.py:81-130). Here:
  - the device timeline comes from jax.profiler (XLA trace viewable in
    TensorBoard/Perfetto) — that is the "fast" profile of the fused step;
  - the per-op table requires running ops one by one, so when op_detail is
    on, Executor.run switches to the eager op-by-op path and records per-op
    wall times (synchronized via block_until_ready), printed at
    stop_profiler sorted by sorted_key, reference-style.
"""
import contextlib
import os
import time

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
           'stop_profiler', 'compiled_op_table']

_state = {'active': False, 'trace_dir': None, 't0': None,
          'op_detail': False, 'events': None}


@contextlib.contextmanager
def cuda_profiler(output_file, output_mode=None, config=None):
    """Compat shim (no CUDA on TPU): behaves like profiler(), with the
    report explicitly routed to `output_file` (the reference wrote the
    nvprof capture there; here it receives the profiler report)."""
    with profiler('All', 'default', profile_path=output_file):
        yield


def op_event_hook():
    """The executor's per-op timing callback, or None when off."""
    if not (_state['active'] and _state['op_detail']):
        return None
    events = _state['events']

    def hook(i, op, dt, env):
        ev = events.setdefault(op.type, [0, 0.0, 0.0, float('inf')])
        ev[0] += 1
        ev[1] += dt
        ev[2] = max(ev[2], dt)
        ev[3] = min(ev[3], dt)

    return hook


def start_profiler(state='All', trace_dir=None, op_detail=False):
    if _state['active']:
        return
    import jax
    trace_dir = trace_dir or os.environ.get('PADDLE_TPU_TRACE_DIR',
                                            '/tmp/paddle_tpu_trace')
    try:
        jax.profiler.start_trace(trace_dir)
        _state['trace_dir'] = trace_dir
    except Exception:
        _state['trace_dir'] = None
    _state['active'] = True
    _state['op_detail'] = bool(op_detail)
    _state['events'] = {}
    _state['t0'] = time.time()


def _event_table(events, sorted_key):
    keyfn = {'calls': lambda kv: kv[1][0],
             'total': lambda kv: kv[1][1],
             'max': lambda kv: kv[1][2],
             'min': lambda kv: kv[1][3],
             'ave': lambda kv: kv[1][1] / kv[1][0]}.get(
                 sorted_key, lambda kv: kv[1][1])
    rows = sorted(events.items(), key=keyfn, reverse=True)
    total_all = sum(ev[1] for _, ev in rows) or 1.0
    lines = ["%-28s %8s %12s %12s %12s %12s %8s" %
             ('Event', 'Calls', 'Total(ms)', 'Min(ms)', 'Max(ms)',
              'Ave(ms)', 'Ratio')]
    for name, (calls, tot, mx, mn) in rows:
        lines.append("%-28s %8d %12.4f %12.4f %12.4f %12.4f %7.2f%%" %
                     (name, calls, tot * 1e3, mn * 1e3, mx * 1e3,
                      tot / calls * 1e3, 100.0 * tot / total_all))
    return "\n".join(lines)


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    if not _state['active']:
        return
    import jax
    if _state['trace_dir'] is not None:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
    wall = time.time() - _state['t0']
    report = ("------------- paddle_tpu profiler -------------\n"
              "wall time: %.3fs\nXLA trace: %s\n" %
              (wall, _state['trace_dir'] or '(trace unavailable)'))
    if _state['events']:
        report += ("\n-------------  op event summary  -------------\n"
                   + _event_table(_state['events'], sorted_key or 'total')
                   + "\n")
    try:
        with open(profile_path, 'w') as f:
            f.write(report)
    except Exception as e:
        # losing the report file silently meant profiled runs "vanished"
        # when profile_path pointed at an unwritable location; the report
        # still prints below, so warn-and-continue is the right severity
        import warnings
        warnings.warn(
            'profiler report could not be written to %r (%s: %s); the '
            'report was only printed to stdout'
            % (profile_path, type(e).__name__, e), RuntimeWarning,
            stacklevel=2)
    print(report)
    _state['active'] = False
    _state['op_detail'] = False
    _state['events'] = None


def reset_profiler():
    _state['t0'] = time.time()
    if _state['events'] is not None:
        _state['events'] = {}


_SCOPE_RE = None


def _scope_of(op_name):
    """Extract the innermost `<fluid_op_type>_<index>` named scope from an
    HLO metadata op_name path. Scopes appear as path segments or inside
    transform brackets: `jit(step)/jvp(mul_3)/dot_general` -> ('mul', 3),
    `jit(step)/sgd_5/sub` -> ('sgd', 5)."""
    import re
    global _SCOPE_RE
    if _SCOPE_RE is None:
        # lookahead for the trailing delimiter so adjacent segments both
        # match ('while_5/mul_3' must yield mul_3, not stop at while_5)
        _SCOPE_RE = re.compile(
            r'(?:^|[/(])([A-Za-z][A-Za-z0-9_]*?)_(\d+)(?=[/)]|$)')
    best = None
    for m in _SCOPE_RE.finditer(op_name):
        best = (m.group(1), int(m.group(2)))  # innermost (last) scope wins
    return best


def compiled_op_table(exe, program=None, feed=None, fetch_list=None,
                      optimized=True, sorted_key='instructions'):
    """Per-Fluid-op attribution of the COMPILED fused step.

    The eager per-op table (op_detail=True) times a DIFFERENT program than
    the one users run — ops dispatched one by one, nothing fused. This
    instead lowers the exact cached XLA module run() executes and
    aggregates its instructions by the `<op_type>_<index>` named scopes
    lowering.run_op stamps (reference profiler.py:81-130 attributes per-op
    inside the real run; post-fusion HLO instruction counts are the
    TPU-native analogue — wall-clock per fused region lives in the
    jax.profiler trace, whose events carry these same scope names).

    Returns (table_text, rows) where rows maps op_type ->
    {'sites': distinct program ops, 'instructions': HLO instruction count}.
    The table is headed by the executor's compile-cache view (exe.cache_stats
    + the lookup this call just made), so the output states WHICH cached
    module it attributed — two tables from different feed signatures are
    different modules, and the key makes that visible.
    """
    text = exe.lowered_hlo(program, feed, fetch_list, optimized=optimized)
    rows = {}
    for line in text.splitlines():
        if 'op_name="' not in line:
            continue
        op_name = line.split('op_name="', 1)[1].split('"', 1)[0]
        scope = _scope_of(op_name)
        if scope is None:
            continue
        op_type, idx = scope
        r = rows.setdefault(op_type, {'sites': set(), 'instructions': 0})
        r['sites'].add(idx)
        r['instructions'] += 1
    for r in rows.values():
        r['sites'] = len(r['sites'])
    order = sorted(rows.items(),
                   key=lambda kv: kv[1].get(sorted_key, 0), reverse=True)
    lines = []
    look = getattr(exe, '_last_cache_lookup', None)
    stats = getattr(exe, 'cache_stats', None)
    if look is not None and stats is not None:
        lines.append(
            'compiled module: cache %s key=%s | entries=%d hits=%d '
            'misses=%d' % (look['outcome'], look['key'], stats['entries'],
                           stats['hits'], stats['misses']))
    lines.append('%-28s %8s %14s' % ('Fluid op', 'Sites', 'HLO instrs'))
    for name, r in order:
        lines.append('%-28s %8d %14d' % (name, r['sites'],
                                         r['instructions']))
    return '\n'.join(lines), rows


@contextlib.contextmanager
def profiler(state='All', sorted_key='default', profile_path='/tmp/profile',
             op_detail=False):
    """Reference fluid.profiler.profiler context manager. The default
    profiles the production fused-jitted step (XLA trace). op_detail=True
    additionally collects the reference-style per-op table — that switches
    Executor.run to eager op-by-op dispatch, which is much slower and is a
    different program than the fused step."""
    start_profiler(state, op_detail=op_detail)
    try:
        yield
    finally:
        # stop even when the profiled body raises: the partial report is
        # exactly what a crashed run needs, and a still-armed profiler
        # would silently force every later Executor.run onto the eager
        # op-by-op path
        stop_profiler(sorted_key, profile_path)
