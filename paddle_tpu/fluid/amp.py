"""Automatic mixed precision (bf16 on the MXU).

Parity: the reference gained fluid.contrib.mixed_precision (fp16 + loss
scaling) for CUDA tensor cores. On TPU the native fast dtype is bfloat16,
whose exponent range equals fp32 — so NO loss scaling is needed: matmul/conv
inputs are cast to bf16 (MXU 2x-8x faster), accumulation stays fp32
(preferred_element_type), master weights and optimizer state stay fp32.

Usage:
    fluid.amp.decorate_program(main_program)      # before Executor.run
or  with fluid.amp.amp_guard(): exe.run(...)
"""
import contextlib

from .framework import default_main_program

__all__ = ['decorate_program', 'amp_guard', 'is_amp']

_global_amp = False


def decorate_program(program=None, enable=True):
    if program is None:
        program = default_main_program()
    program._amp = bool(enable)
    program._bump_version()
    return program


@contextlib.contextmanager
def amp_guard(enable=True):
    global _global_amp
    prev = _global_amp
    _global_amp = enable
    try:
        yield
    finally:
        _global_amp = prev


def is_amp(program=None):
    if _global_amp:
        return True
    return bool(getattr(program, '_amp', False)) if program is not None \
        else False
