"""fluid.passes — ahead-of-lowering Program->Program optimization.

PR 5's `fluid.analysis` proved facts about the Program IR (def-use,
shape/dtype propagation, donation safety); this package aims the same
facts at SPEED. Every execution path — train `run`, `run_bundle`'s scan,
the serving engine, `export_compiled` — shares one lowering, so a
pipeline of Program->Program transforms applied just before that lowering
makes all of them faster at once, the way classic graph-compiler stacks
(and the reference's own memory_optimization_transpiler /
inference_transpiler) pre-digest the graph before codegen.

Passes (docs/passes.md has the catalog and the A/B guarantees):

  amp   — AMP as an IR rewrite: explicit `cast` ops around the
          matmul/conv/attention ops `lowering.amp_cast` used to cast at
          trace time, so bf16 boundaries are visible to analysis,
          provenance, and program_lint. `ctx.amp` stays only as the
          compatibility flag for unoptimized programs.
  fold  — constant folding: ops whose inputs are all compile-time
          constants (`fill_constant`/`assign_value` chains) are evaluated
          through THEIR OWN lowering rules (one definition of op
          semantics) and replaced by `assign_value`.
  cse   — common subexpression elimination: ops hashed by
          (type, attrs, canonicalized input values) within the top-level
          block, def-use-safe, pure ops only.
  dce   — dead-op elimination: `analysis.live_mask` (the DeadOp finding's
          own liveness) promoted to a pruning transform that respects
          fetch and persistable liveness.

Equivalence contract: DCE/CSE/folding are BIT-EXACT against the
unoptimized lowering (per-op RNG streams survive op removal via the
`op_seq` stamp the executor consults); the AMP rewrite matches runtime
AMP within one bf16 rounding of each rewritten op's output
(docs/passes.md "A/B guarantees"). `tests/test_passes.py` drills both
claims over the program-fuzz corpus and the book models.

Wiring: `PADDLE_TPU_OPT={off,default,aggressive}` gates the Executor
(once per compiled-step cache key, like PADDLE_TPU_VERIFY);
`Program.optimize()` is the manual surface; `tools/program_lint.py
--optimize` reports what the passes would do to a saved artifact.
Telemetry: every pass runs under a `passes.<name>` span and bumps
`passes.<name>.ops_removed` / `.ops_inserted` counters, and the whole
pipeline records `passes.optimize` with the total op-count delta, so
`obs_report` and `bench_sentinel` can attribute wins to passes.
"""
import functools
import inspect
import os

from ... import obs
from .. import lowering
from ..analysis.dataflow import sub_block_indices

from .memplan import MemoryPlan, memory_plan  # noqa: F401  (re-export)

__all__ = ['optimize', 'opt_mode', 'is_pure', 'is_foldable',
           'MemoryPlan', 'memory_plan', 'ENV_OPT', 'LEVELS', 'OP_SEQ_ATTR']

# PADDLE_TPU_OPT wires optimize() into Executor._prepare, once per
# compiled-step cache key:
#   off        (default) — lower the program exactly as built;
#   default    — amp rewrite, constant folding, CSE, DCE (bit-exact /
#                documented-tolerance transforms only);
#   aggressive — same passes with a larger constant-folding budget.
ENV_OPT = 'PADDLE_TPU_OPT'
LEVELS = ('off', 'default', 'aggressive')

# Original top-level op index, stamped on every op of the optimized clone
# BEFORE any structural change. The executor derives each op's RNG stream
# from this attr (falling back to the list position), so removing or
# merging ops never shifts another op's dropout mask — the keystone of
# the bit-exactness guarantee.
OP_SEQ_ATTR = 'op_seq'

_C_PROGRAMS = obs.counter('passes.programs_optimized')
_C_REMOVED = obs.counter('passes.ops_removed')


def opt_mode():
    v = os.environ.get(ENV_OPT, 'off').strip().lower()
    if v in ('', '0', 'off', 'false', 'no', 'none'):
        return 'off'
    if v in ('default', '1', 'on', 'true'):
        return 'default'
    if v == 'aggressive':
        return 'aggressive'
    raise ValueError(
        '%s must be one of off|default|aggressive, got %r' % (ENV_OPT, v))


# -- purity ------------------------------------------------------------------
# A pass may only touch ops it can PROVE are pure functions of their
# inputs. Rather than a hand-curated list that silently rots as ops are
# added, the proof is mechanical: the op must have a plain lowering rule
# (no block rule, no sub-blocks) whose SOURCE never touches the PRNG
# stream — a rule that mentions ctx.rng is impure on every code path,
# conservatively. Folding is stricter still: the rule must not branch on
# the compilation context (platform/mesh), because folding evaluates it
# OUTSIDE the compiled module.

_EFFECTFUL = frozenset(['print', 'autodiff', 'py_func'])


@functools.lru_cache(maxsize=None)
def _rule_source(op_type):
    try:
        return inspect.getsource(lowering.get_rule(op_type))
    except Exception:
        return None


@functools.lru_cache(maxsize=None)
def _rule_uses_rng(op_type):
    src = _rule_source(op_type)
    return src is None or 'rng(' in src


@functools.lru_cache(maxsize=None)
def _rule_uses_context(op_type):
    src = _rule_source(op_type)
    return src is None or any(m in src for m in (
        'ctx.platform', 'ctx.mesh', 'manual_axes', 'ctx.is_test'))


def is_pure(op):
    """True when the op is a deterministic pure function of its inputs:
    safe to deduplicate (CSE) and to drop when dead (DCE still keeps
    effectful ops explicitly)."""
    if op.type in _EFFECTFUL or op.type in lowering._BLOCK_RULES:
        return False
    if not lowering.has_rule(op.type):
        return False
    if sub_block_indices(op):
        return False
    return not _rule_uses_rng(op.type)


def is_foldable(op):
    """Pure AND context-free: the rule can be evaluated eagerly at
    optimization time with the same result the compiled module would
    produce (no platform/mesh/is_test branching)."""
    return is_pure(op) and not _rule_uses_context(op.type)


def written_names(program, op, cache=None):
    """Every name `op` writes at its position in a top-level walk: the
    declared outputs PLUS every name its sub-blocks write — while/ifelse
    bodies legally update outer names (persistables included) without
    listing them as the parent op's outputs. Any pass keeping a
    name->version map over the walk must bump with THIS set, or two
    reads straddling an undeclared sub-block write would look like the
    same value. `cache` memoizes the sub-block walk (dataflow's
    _block_writes memo, block idx -> names)."""
    from ..analysis.dataflow import _block_writes
    names = set(op.output_arg_names)
    for bi in sub_block_indices(op, program):
        names |= _block_writes(program, program.block(bi), cache=cache)
    return names


def write_counts(program):
    """name -> number of writes program-wide (all blocks), counting the
    names `autodiff` defines via attrs (grad_names) as writes. The
    written-exactly-once test both fold and cse build their SSA-ness
    guarantees on — one definition, so the passes can never disagree."""
    counts = {}
    for blk in program.blocks:
        for op in blk.ops:
            for n in op.output_arg_names:
                counts[n] = counts.get(n, 0) + 1
            if op.type == 'autodiff':
                for n in op.attrs.get('grad_names', ()):
                    counts[n] = counts.get(n, 0) + 1
    return counts


# -- report ------------------------------------------------------------------

# the one number per pass the passes.optimize span (and obs_report's
# attribution line) carries: actual WORK DONE, never a grab-bag sum that
# would count amp's skipped ops as rewrites
_PRIMARY_STAT = {'dce': 'ops_removed', 'fold': 'ops_folded',
                 'cse': 'ops_merged', 'amp': 'ops_rewritten',
                 'quant': 'ops_rewritten'}

class PassReport(object):
    """What one optimize() run did: per-pass numbers + the total top-level
    op-count delta. Rendered by program_lint --optimize; attached to the
    optimized program as `_opt_report`."""

    def __init__(self, level):
        self.level = level
        self.passes = {}       # name -> {stat: int}
        self.ops_before = 0
        self.ops_after = 0
        self.skipped = None    # reason string when nothing ran

    def note(self, name, **stats):
        d = self.passes.setdefault(name, {})
        for k, v in stats.items():
            d[k] = d.get(k, 0) + int(v)

    def to_dict(self):
        return {'level': self.level, 'ops_before': self.ops_before,
                'ops_after': self.ops_after, 'skipped': self.skipped,
                'passes': {k: dict(v) for k, v in self.passes.items()}}

    def __repr__(self):
        if self.skipped:
            return 'PassReport(skipped=%r)' % self.skipped
        per = ', '.join('%s=%s' % (k, v)
                        for k, v in sorted(self.passes.items()))
        return 'PassReport(level=%s, ops %d -> %d%s)' % (
            self.level, self.ops_before, self.ops_after,
            '; ' + per if per else '')


# -- the pipeline ------------------------------------------------------------

def _clone_for_opt(program):
    """A deep copy the passes may mutate freely, carrying every execution
    flag run() consults (clone() already moves _amp/_fetch_f32/_use_remat/
    _dist_config; the anomaly guard travels here) and stamped with each
    op's original index for RNG-stream stability."""
    p = program.clone(for_test=False)
    for flag in ('_anomaly_guard', '_anomaly_guard_max_skips'):
        if hasattr(program, flag):
            setattr(p, flag, getattr(program, flag))
    for i, op in enumerate(p.global_block().ops):
        op.attrs.setdefault(OP_SEQ_ATTR, i)
    return p


def optimize(program, feeds=None, fetches=None, level='default',
             where=None):
    """Run the pass pipeline over `program`; returns (optimized_program,
    PassReport). The input program is NEVER mutated — the result is an
    optimized clone (possibly the input itself when nothing can run).

    feeds/fetches: the execution context, exactly as analysis.analyze
    takes them. fetches gates DCE (one run's fetch subset IS dead-code
    evidence here, because the optimized clone is cached per fetch set —
    unlike the verifier, which must stay quiet about it).
    """
    if level not in LEVELS:
        raise ValueError('optimize level must be one of %s, got %r'
                         % ('|'.join(LEVELS), level))
    report = PassReport(level)
    if level == 'off':
        report.skipped = 'level=off'
        return program, report
    if getattr(program, '_pipeline_config', None) is not None:
        # the GPipe region depends on contiguous op ranges derived from
        # device_guard stamps; structural surgery would silently demote
        # the region to sequential execution — leave pipelined programs
        # to the lowering they were transpiled for
        report.skipped = 'pipeline-transpiled program'
        return program, report

    from . import amp_pass, cse, dce, fold, quant_pass
    from .. import amp as amp_mod

    with obs.span('passes.optimize', level=level,
                  where=where or 'api') as sp:
        p = _clone_for_opt(program)
        report.ops_before = len(p.global_block().ops)
        if amp_mod.is_amp(program):
            with obs.span('passes.amp'):
                amp_pass.run(p, report)
        if quant_pass.is_quant(program):
            with obs.span('passes.quant'):
                quant_pass.run(p, report)
        with obs.span('passes.fold'):
            fold.run(p, report, level=level)
        if fetches is not None:
            # CSE and DCE both ELIMINATE output names; without knowing
            # the fetch set, any terminal output may be fetched later —
            # only the amp/fold rewrites (which preserve every name) are
            # safe to run blind
            with obs.span('passes.cse'):
                cse.run(p, report, feeds=feeds, fetches=fetches)
            with obs.span('passes.dce'):
                dce.run(p, report, fetches=fetches)
        # Self-check: a pass bug must surface HERE — where the executor's
        # fallback catches it and lowers the unoptimized program — not as
        # a raw KeyError at trace time. One cheap def-use walk over the
        # result (no shape propagation, no DeadOp noise).
        from ..analysis import dataflow as _dataflow
        from ..analysis.findings import SEV_ERROR
        errs = [f for f in _dataflow.run_pass(p, feeds=feeds,
                                              fetches=fetches,
                                              dead_ops=False)
                if f.severity == SEV_ERROR]
        if errs:
            raise RuntimeError(
                'optimizer produced an invalid program (%d error '
                'finding(s)):\n%s'
                % (len(errs), '\n'.join('  %s' % f for f in errs)))
        report.ops_after = len(p.global_block().ops)
        sp.fields.update(ops_before=report.ops_before,
                         ops_after=report.ops_after,
                         **{k: v.get(_PRIMARY_STAT.get(k),
                                     sum(v.values()))
                            for k, v in report.passes.items()})
    _C_PROGRAMS.inc()
    if report.ops_before > report.ops_after:
        _C_REMOVED.inc(report.ops_before - report.ops_after)
    p._opt_report = report
    p._bump_version()
    return p, report
