"""Constant folding: evaluate constant subgraphs at optimization time.

Constant sources are `fill_constant` and `assign_value` ops (no inputs,
value fully determined by attrs). Any op that is FOLDABLE
(passes.is_foldable: pure, no RNG, no platform/mesh branching) and whose
inputs are all constants is evaluated through ITS OWN lowering rule —
the same function the compiled step traces, so there is exactly one
definition of op semantics — and replaced by an `assign_value` op
carrying the result. The now-unconsumed constant producers are left for
DCE to sweep.

Budget: results larger than the level's element cap are not folded (the
folded values live in op attrs — a weights-sized constant would bloat
the program and pin memory twice). `default` caps at 4096 elements,
`aggressive` at 262144.

Also hosted here: `fold_batch_norm` — the conv+BN weight fold the
deprecated InferenceTranspiler now delegates to (it rewrites SCOPE
weights, not graph constants, so it lives beside — not inside — the
attrs-level folding above).

Bit-exactness caveat (docs/passes.md): evaluation happens eagerly on the
host's default backend; an op folded here but executed inside the fused
module on another backend could differ in the last ulp for
transcendentals. The fold runs only context-free rules and the A/B
suite pins the guarantee on the platform it runs on.
"""
import numpy as np

import jax.numpy as jnp

from ... import obs
from .. import lowering
from ..framework import Operator
from . import is_foldable

__all__ = ['run', 'fold_batch_norm']

_C_FOLDED = obs.counter('passes.fold.ops_folded')

_CAPS = {'default': 4096, 'aggressive': 1 << 18}

# value-from-attrs constant producers (seed the lattice; no inputs)
_SOURCES = frozenset(['fill_constant', 'assign_value'])

# sources larger than this are never even MATERIALIZED into the constant
# lattice — a startup program's vocab-sized zero accumulators must not
# cost the optimizer hundreds of MB of eager allocations it would throw
# away (the replacement cap above is separate and much smaller)
_SOURCE_CAP = 1 << 20


def _source_size(op):
    shape = op.attrs.get('shape') or ()
    n = 1
    for d in shape:
        n *= max(int(d), 1) if isinstance(d, int) else 1
    return n


def _eval_rule(op, const_vals):
    """Run the op's lowering rule on concrete constant inputs. Returns
    {slot: [array, ...]} or None when the result is unusable (SeqValue /
    None outputs)."""
    import jax
    ins = {slot: [const_vals[v.name] for v in vs]
           for slot, vs in op.inputs.items()}
    ctx = lowering.Ctx(jax.random.key(0), op_index=0)
    outs = lowering.get_rule(op.type)(ins, op.attrs, ctx)
    result = {}
    for slot, vs in op.outputs.items():
        vals = outs.get(slot) if hasattr(outs, 'get') else None
        if vals is None:
            return None
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        if len(vals) < len(vs):
            return None
        row = []
        for val in vals[:len(vs)]:
            if not hasattr(val, 'shape') or isinstance(val, lowering.SeqValue):
                return None
            row.append(jnp.asarray(val))
        result[slot] = row
    return result


def _const_op(block, var, value, src_op):
    """An assign_value op binding `var` to the folded `value`, carrying
    the folded op's provenance and RNG seq stamp."""
    arr = np.asarray(value)
    dtype = ('bfloat16' if arr.dtype == jnp.bfloat16
             else str(arr.dtype))
    if arr.dtype == jnp.bfloat16:
        arr = arr.astype(np.float32)   # tolist()-able; exact (bf16 ⊂ f32)
    attrs = {'values': arr.tolist(), 'shape': list(arr.shape),
             'dtype': dtype}
    for carry in ('op_seq', 'op_role'):
        if carry in src_op.attrs:
            attrs[carry] = src_op.attrs[carry]
    return Operator(block, type='assign_value', inputs={},
                    outputs={'Out': [var]}, attrs=attrs,
                    callsite=src_op.callsite)


def run(program, report, level='default'):
    """Fold constant top-level subgraphs in place. Returns ops folded."""
    from . import write_counts as _write_counts
    cap = _CAPS.get(level, _CAPS['default'])
    block = program.global_block()
    write_counts = _write_counts(program)

    const_vals = {}   # name -> concrete value (producers written once)
    folded = 0
    for i, op in enumerate(block.ops):
        out_names = op.output_arg_names
        ssa = all(write_counts.get(n, 0) == 1 for n in out_names)
        if (op.type in _SOURCES and ssa and not op.inputs
                and _source_size(op) <= _SOURCE_CAP):
            try:
                vals = _eval_rule(op, const_vals)
            except Exception:
                vals = None
            if vals is not None:
                for slot, vs in op.outputs.items():
                    for v, val in zip(vs, vals[slot]):
                        const_vals[v.name] = val
            continue
        if (ssa and out_names and is_foldable(op) and op.inputs
                and all(v.name in const_vals
                        for vs in op.inputs.values() for v in vs)):
            try:
                vals = _eval_rule(op, const_vals)
            except Exception:
                vals = None
            if vals is not None and all(
                    v.size <= cap for row in vals.values() for v in row):
                # single-output ops fold to ONE assign_value; multi-output
                # ops would need one per output — rare enough to skip
                slots = [(s, vs) for s, vs in op.outputs.items() if vs]
                if len(slots) == 1 and len(slots[0][1]) == 1:
                    slot, var = slots[0][0], slots[0][1][0]
                    val = vals[slot][0]
                    block.ops[i] = _const_op(block, var, val, op)
                    const_vals[var.name] = val
                    folded += 1
                    continue
                # not replaced, but the VALUE is still known — later
                # consumers can fold through it
                for slot, vs in op.outputs.items():
                    for v, val in zip(vs, vals[slot]):
                        const_vals[v.name] = val
            continue
        for n in out_names:
            const_vals.pop(n, None)   # overwritten: no longer constant
    if folded:
        program._bump_version()
        _C_FOLDED.inc(folded)
    report.note('fold', ops_folded=folded)
    return folded


def fold_batch_norm(program, scope):
    """Fold `batch_norm` (is_test) into a preceding `conv2d` whose output
    has no other consumer: the conv weights are rescaled in the SCOPE by
    the BN statistics and the BN op becomes a bias `elementwise_add` —
    the reference inference_transpiler's transform, now owned by the
    passes layer (the transpiler is a deprecated shim over this)."""
    block = program.global_block()
    folded = 0
    i = 0
    while i < len(block.ops) - 1:
        op = block.ops[i]
        nxt = block.ops[i + 1]
        if op.type == 'conv2d' and nxt.type == 'batch_norm' and \
                nxt.inputs['X'][0].name == op.outputs['Output'][0].name:
            scale_v = scope.vars.get(nxt.inputs['Scale'][0].name)
            bias_v = scope.vars.get(nxt.inputs['Bias'][0].name)
            mean_v = scope.vars.get(nxt.inputs['Mean'][0].name)
            var_v = scope.vars.get(nxt.inputs['Variance'][0].name)
            w_name = op.inputs['Filter'][0].name
            w = scope.vars.get(w_name)
            if any(v is None for v in (scale_v, bias_v, mean_v, var_v, w)):
                i += 1
                continue
            eps = nxt.attrs.get('epsilon', 1e-5)
            scale = np.asarray(scale_v)
            bias = np.asarray(bias_v)
            mean = np.asarray(mean_v)
            var = np.asarray(var_v)
            wnp = np.asarray(w)
            inv = scale / np.sqrt(var + eps)
            scope.vars[w_name] = jnp.asarray(wnp * inv[:, None, None, None])
            new_bias = bias - mean * inv
            bias_var = block.create_var(
                name=w_name + '.bnfold_bias', shape=list(new_bias.shape),
                dtype='float32', persistable=True)
            scope.vars[bias_var.name] = jnp.asarray(new_bias)
            bn_out = nxt.outputs['Y'][0]
            # channel axis follows the conv's layout
            ch_axis = (-1 if op.attrs.get('data_format', 'NCHW') == 'NHWC'
                       else 1)
            block.ops[i + 1] = Operator(
                block, type='elementwise_add',
                inputs={'X': op.outputs['Output'], 'Y': [bias_var]},
                outputs={'Out': [bn_out]}, attrs={'axis': ch_axis},
                callsite=nxt.callsite)
            program._bump_version()
            folded += 1
        i += 1
    return folded
