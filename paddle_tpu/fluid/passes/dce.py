"""Dead-op elimination: the verifier's DeadOp finding, promoted from a
warning to a pruning transform.

`analysis.live_mask` — the exact liveness the DeadOp finding is built on
(an op is live when its outputs transitively reach a fetch or a
persistable write, sub-block persistable writes included) — decides what
to drop. The executor's verify wiring deliberately SKIPS the DeadOp
finding because one run's fetch subset is not dead-code evidence for the
program in general; here it is exactly the right evidence, because the
optimized clone is cached per (feed-sig, fetch) key: a different fetch
list gets its own clone with its own liveness.

Beyond liveness, the transform keeps:
  * effectful ops (print and friends) and ops with no lowering rule —
    removing an op the lowering would have rejected silently changes a
    loud failure into a quiet success;
  * ops with sub-blocks whose liveness says dead — they ARE dead (the
    mask accounts for their persistable writes), and dropping them drops
    the trace cost of the whole body.

Bit-exactness: removal never reindexes another op's RNG stream — the
executor reads each op's `op_seq` stamp (passes.OP_SEQ_ATTR), not its
list position.
"""
from ... import obs
from .. import lowering
from ..analysis.dataflow import live_mask, op_writes

__all__ = ['run']

_C_REMOVED = obs.counter('passes.dce.ops_removed')

# ops whose execution is the point, whatever dataflow says
_KEEP = frozenset(['print'])


def _must_keep(op):
    if op.type in _KEEP:
        return True
    if op.type == 'autodiff':
        # the liveness walk itself decides autodiff (live iff a grad
        # feeds a live consumer); never force-keep it here
        return False
    return not (lowering.has_rule(op.type)
                or op.type in lowering._BLOCK_RULES)


def run(program, report, fetches):
    """Drop dead top-level ops from `program` (in place — `program` is
    optimize()'s private clone). Returns the number removed."""
    block = program.global_block()
    # _must_keep rides INSIDE the liveness walk (not as a post-filter):
    # a retained print op's producers must stay live too, or the kept op
    # would read a name nothing defines at lowering time
    live = live_mask(program, block, set(fetches), keep=_must_keep)
    keep, dropped = [], []
    for op, l in zip(block.ops, live):
        if l:
            keep.append(op)
        else:
            dropped.append(op)
    if dropped:
        block.ops = keep
        program._bump_version()
        _C_REMOVED.inc(len(dropped))
    report.note('dce', ops_removed=len(dropped))
    return len(dropped)
