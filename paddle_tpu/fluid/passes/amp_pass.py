"""AMP as an IR rewrite: explicit casts instead of trace-time casting.

Runtime AMP (`lowering.amp_cast`, armed by `ctx.amp`) silently casts the
float32 operands of matmul/conv/attention ops to bfloat16 inside the
rule — invisible to the Program IR, to `fluid.analysis`, to provenance,
and to `program_lint`. This pass makes the same decision VISIBLE: for
each AMP-eligible op it inserts `cast` ops (f32 -> bf16) in front of the
op's float operands, repoints the op at the casted temps, and — when the
rule's inferred output is bf16 where the var declared f32 — routes the
op through a bf16 temp and casts back to f32, so downstream ops see
exactly the dtype runtime AMP produced. The rewritten program then runs
with `ctx.amp` OFF (`program._amp_ir` marks it); `ctx.amp` remains only
as the compatibility flag for unoptimized programs.

Numerics vs runtime AMP (the documented tolerance, docs/passes.md): the
op's result passes through one extra f32->bf16 rounding at the region
boundary (runtime AMP casts the result straight back to f32 inside the
rule; here the boundary is a real bf16 value a cast op widens). Relative
error is bounded by one bf16 ulp (~2^-8) of the op output; everything
outside the rewritten regions is bit-identical.

Eligibility is decided per op: when the rule cannot abstract-eval on the
hypothetical bf16 operand specs, the op is left on f32 (MORE precise
than runtime AMP, still within the documented tolerance) and counted in
the report.
"""
import jax

from ... import obs
from .. import lowering
from ..framework import Operator
from . import OP_SEQ_ATTR

__all__ = ['run', 'AMP_SLOTS']

_C_CASTS = obs.counter('passes.amp.casts_inserted')
_C_REWRITTEN = obs.counter('passes.amp.ops_rewritten')

# op type -> input slots runtime amp_cast covers (None = every slot, the
# moe rule casts its whole param bundle)
AMP_SLOTS = {
    'mul': ('X', 'Y'),
    'matmul': ('X', 'Y'),
    'conv2d': ('Input', 'Filter'),
    'flash_attention': ('Q', 'K', 'V'),
    'moe_mlp': None,
}


def _bf16_spec(spec):
    if isinstance(spec, lowering.SeqValue):
        return lowering.SeqValue(_bf16_spec(spec.data), spec.lengths,
                                 spec.outer_lengths)
    return jax.ShapeDtypeStruct(spec.shape, 'bfloat16')


def _cast_op(block, src, dst, dtype, seq_attr):
    return Operator(block, type='cast', inputs={'X': [src]},
                    outputs={'Out': [dst]},
                    attrs={'out_dtype': dtype, OP_SEQ_ATTR: seq_attr},
                    callsite=getattr(src.op, 'callsite', None))


def run(program, report):
    """Rewrite AMP regions in place (program is optimize()'s clone).
    Returns the number of ops rewritten."""
    from . import written_names
    block = program.global_block()
    version = {}            # name -> write version (the block is not SSA)
    cast_cache = {}         # (name, version) -> casted Variable
    new_ops = []
    inserted = rewritten = skipped = 0
    bw_cache = {}

    def bump(op):
        # written_names, not output_arg_names: an undeclared sub-block
        # write (while body updating an outer f32 var) must invalidate
        # the cast_cache entry for that name
        for n in written_names(program, op, cache=bw_cache):
            version[n] = version.get(n, 0) + 1

    for op in block.ops:
        if op.type not in AMP_SLOTS:
            new_ops.append(op)
            bump(op)
            continue
        slots = AMP_SLOTS[op.type]
        targets = []
        in_specs, specs_ok = {}, True
        for slot, vs in op.inputs.items():
            row = []
            for j, v in enumerate(vs):
                s = lowering.spec_of(v)
                if s is None:
                    specs_ok = False
                row.append(s)
                if (v.dtype == 'float32'
                        and (slots is None or slot in slots)):
                    targets.append((slot, j, v))
            in_specs[slot] = row
        if not targets:
            new_ops.append(op)
            bump(op)
            continue
        outs = None
        if specs_ok:
            for slot, j, v in targets:
                in_specs[slot][j] = _bf16_spec(in_specs[slot][j])
            try:
                outs = lowering.abstract_eval(op, in_specs)
            except Exception:
                outs = None
        if outs is None:
            # cannot prove the rewrite's dtypes: leave the op on f32
            # (more precise than runtime amp; documented tolerance)
            skipped += 1
            new_ops.append(op)
            bump(op)
            continue
        seq = op.attrs.get(OP_SEQ_ATTR, 0)
        orig_out_names = list(op.output_arg_names)
        for slot, j, v in targets:
            ck = (v.name, version.get(v.name, 0))
            cv = cast_cache.get(ck)
            if cv is None:
                cv = block.create_var(
                    name='%s@amp.v%d.bf16' % (v.name, ck[1]),
                    shape=list(v.shape) if v.shape is not None else None,
                    dtype='bfloat16', lod_level=v.lod_level)
                new_ops.append(_cast_op(block, v, cv, 'bfloat16', seq))
                cast_cache[ck] = cv
                inserted += 1
            op.inputs[slot][j] = cv
        new_ops.append(op)
        # bf16 outputs where f32 was declared: route through a bf16 temp
        # and cast back, so downstream dtypes match runtime amp exactly
        for slot, vs in op.outputs.items():
            vals = outs.get(slot) if hasattr(outs, 'get') else None
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for j, (var, val) in enumerate(zip(vs, vals)):
                if val is None:
                    continue
                spec = val.data if isinstance(val, lowering.SeqValue) \
                    else val
                if str(spec.dtype) == 'bfloat16' and var.dtype == 'float32':
                    ov = block.create_var(
                        name=var.name + '@amp.out.bf16',
                        shape=(list(var.shape) if var.shape is not None
                               else None),
                        dtype='bfloat16', lod_level=var.lod_level)
                    ov.op = op
                    op.outputs[slot][j] = ov
                    new_ops.append(_cast_op(block, ov, var, 'float32',
                                            seq))
                    inserted += 1
        for n in orig_out_names:
            version[n] = version.get(n, 0) + 1
        rewritten += 1

    if rewritten or inserted:
        block.ops = new_ops
        program._bump_version()
        _C_CASTS.inc(inserted)
        _C_REWRITTEN.inc(rewritten)
    # the rewritten program must NOT also runtime-cast: amp becomes an
    # IR property; _amp_ir tells the executor to force ctx.amp off even
    # when the global amp_guard armed it
    program._amp = False
    program._amp_ir = True
    report.note('amp', ops_rewritten=rewritten, casts_inserted=inserted,
                ops_skipped=skipped)
    return rewritten
