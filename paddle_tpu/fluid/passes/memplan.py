"""Per-program donation/memory plan.

The executor used to make its buffer-donation decision inline
(`_CompiledStep.__init__` scanned `analysis.executor_write_set` and, when
mutating, donated EVERY persistable input and re-exposed every one as an
output). This module turns that ad-hoc decision into a first-class plan
object computed from the same analysis facts:

  * `write_set`   — persistable names the top-level block writes (the
                    shared `analysis.executor_write_set`, so the static
                    donation-safety pass cross-checks THIS plan, not a
                    copy of it);
  * `donates`     — whether the step donates at all (a read-only step
                    donates nothing: donation would invalidate parameter
                    buffers under concurrent runs — the PR-3 serving
                    class);
  * donated vs read-only SPLIT — only the buffers the step actually
    writes are donated and re-exposed as outputs. Read-only persistables
    (frozen weights, inference-time BN statistics, embedding tables on a
    scoring step) keep their scope buffers valid and leave the module's
    output list — XLA no longer carries a passthrough copy per step, and
    the donated set is exactly the set XLA can alias in place, which is
    what keeps the update fusible with the compute that produced it.

Consumers: `executor._CompiledStep` (jit donation + write-back),
`Executor.run_bundle` (the scan-carry gap check names the plan's
uninitialized writes), and the serving engine's `warmup()` (records the
plan in its spans and rejects donating models behind a concurrent
engine).
"""

__all__ = ['MemoryPlan', 'memory_plan']


class MemoryPlan(object):
    """Donation/write-back plan for one Program (see module docstring)."""

    __slots__ = ('write_set', 'donates')

    def __init__(self, write_set):
        self.write_set = frozenset(write_set)
        self.donates = bool(self.write_set)

    def donate_names(self, persist_in):
        """Persistable inputs the step donates (and re-exposes as
        outputs): exactly the initialized ones it writes."""
        return sorted(n for n in persist_in if n in self.write_set)

    def readonly_names(self, persist_in):
        """Persistable inputs the step only reads: not donated, not
        re-exposed — their scope buffers stay valid across the call."""
        return sorted(n for n in persist_in if n not in self.write_set)

    def split(self, persist):
        """(donated, readonly) dicts from a full persist dict."""
        donated = {n: v for n, v in persist.items() if n in self.write_set}
        readonly = {n: v for n, v in persist.items()
                    if n not in self.write_set}
        return donated, readonly

    def uninitialized(self, persist_in):
        """Writes with no scope value yet — the run_bundle scan-carry gap
        (and the startup-program case: outputs created by the step)."""
        return sorted(self.write_set - set(persist_in))

    def persist_out(self):
        """Names the compiled step writes back to the scope."""
        return sorted(self.write_set)

    def donation_vector(self, persist_in):
        """pjit-style donation vector over the compiled step's
        (donated, readonly, feed, rng_key) argument list: exactly the
        written-persistables argument is donated, and only when the step
        writes at all (the pjit `donation_vector`/`rebase_donate_argnums`
        idiom, collapsed onto the executor's fixed 4-arg signature)."""
        return (bool(self.donate_names(persist_in)), False, False, False)

    def donate_argnums(self, persist_in):
        """The donate_argnums tuple jax.jit takes, derived from
        donation_vector — one definition of the donation decision for
        both the plain and the GSPMD-annotated jit paths."""
        return tuple(i for i, d in enumerate(self.donation_vector(persist_in))
                     if d)

    def sharding_plan(self, persist_in, shardings, default=None):
        """(donated_in, readonly_in, persist_out) NamedSharding trees for
        the GSPMD executor path (docs/parallel.md): the donated argument's
        in-shardings and the persistable outputs' out-shardings are THE
        SAME objects, so the compiled step's state keeps one stable layout
        across steps/scan carries — XLA never inserts a resharding (or a
        full rematerialization) between a step's output and the next
        step's input.

        shardings: name -> NamedSharding (or None = unconstrained) for
        values present in the scope; `default` fills persistable outputs
        the step CREATES (startup programs). Entries missing from both
        stay None (jit leaves them unconstrained)."""
        donated = {n: shardings.get(n, default)
                   for n in self.donate_names(persist_in)}
        readonly = {n: shardings.get(n, default)
                    for n in self.readonly_names(persist_in)}
        out = {n: shardings.get(n, default) for n in self.persist_out()}
        return donated, readonly, out

    def to_dict(self):
        return {'donates': self.donates,
                'write_set': sorted(self.write_set)}

    def __repr__(self):
        return 'MemoryPlan(donates=%s, writes=%d)' % (
            self.donates, len(self.write_set))


def memory_plan(program):
    """The donation/memory plan for `program`, derived from the SAME
    write-set the static donation-safety pass verifies."""
    from ..analysis import executor_write_set
    return MemoryPlan(executor_write_set(program))
