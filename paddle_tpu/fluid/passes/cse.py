"""Common subexpression elimination over the top-level block.

Two ops compute the same value when they have the same type, the same
attrs (modulo positional metadata: op_seq/op_role/op_device), and their
inputs refer to the same VALUES — not just the same names: the block is
not SSA, so each name carries a version number bumped at every write,
and the hash key uses (name, version) pairs resolved through the alias
map of merges already made (so chains of duplicates collapse in one
walk).

Def-use safety — a duplicate is merged only when:
  * the op is pure (passes.is_pure: plain rule, no RNG — two dropouts
    are never "the same computation");
  * every output name is written exactly ONCE program-wide (merging a
    name that is later rewritten would redirect reads across the
    rewrite) — true for the unique_name temps that make up virtually
    every duplicate in practice;
  * no output is a feed, a fetch target, a persistable, or a name an
    `autodiff` op references by attr (loss/param/grad names are string
    references the rename walk cannot see).

A merged op is REMOVED and every later read of its outputs (sub-blocks
included — bodies legally read outer names) is redirected to the kept
op's outputs. RNG streams are unaffected by the removal: the executor
reads op_seq stamps, not list positions.
"""
from ... import obs
from . import OP_SEQ_ATTR, is_pure

__all__ = ['run']

_C_MERGED = obs.counter('passes.cse.ops_merged')

_KEY_SKIP_ATTRS = frozenset([OP_SEQ_ATTR, 'op_role', 'op_device',
                             'op_namescope'])


def _freeze(v):
    if isinstance(v, dict):
        return ('d',) + tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple)):
        return ('l',) + tuple(_freeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return ('s',) + tuple(sorted(_freeze(x) for x in v))
    return v


def run(program, report, feeds=None, fetches=None):
    """Merge duplicate pure ops in place. Returns ops merged."""
    from . import write_counts as _write_counts
    from . import written_names as _written_names
    block = program.global_block()
    var_names = {v.name for v in program.list_vars()}
    persistables = {v.name for v in program.list_vars() if v.persistable}
    protected = set(fetches or ()) | set(feeds or ())
    write_counts = _write_counts(program)
    # Attr-level string references the rename walk cannot see: autodiff
    # (loss/param/grad names) is the famous one, but control-flow rules
    # read env by attr name too (switch cond_names, static_rnn step_ins/
    # mems, dynamic_rnn slots). Rather than enumerate rule internals,
    # protect EVERY attr string (and string inside an attr list) that
    # names a program variable — over-protection only costs a missed
    # merge, never a dangling name.
    def _collect(v):
        if isinstance(v, str):
            if v in var_names:
                protected.add(v)
        elif isinstance(v, dict):
            for x in v.values():
                _collect(x)
        elif isinstance(v, (list, tuple, set, frozenset)):
            for x in v:
                _collect(x)

    for blk in program.blocks:
        for op in blk.ops:
            for v in op.attrs.values():
                _collect(v)

    version = {}   # name -> write version at the walk's current position
    alias = {}     # merged name -> surviving name

    def resolve(n):
        while n in alias:
            n = alias[n]
        return n

    seen = {}      # value key -> op
    merged_ops = set()
    merged = 0
    bw_cache = {}  # _block_writes memo for the version bumps below
    for op in block.ops:
        out_names = op.output_arg_names
        mergeable = (
            is_pure(op) and out_names
            and all(write_counts.get(n, 0) == 1 for n in out_names)
            and not any(n in persistables or n in protected
                        for n in out_names))
        if mergeable:
            key = (op.type,
                   _freeze({k: v for k, v in op.attrs.items()
                            if k not in _KEY_SKIP_ATTRS}),
                   tuple(sorted(
                       (slot, tuple((resolve(v.name),
                                     version.get(resolve(v.name), 0))
                                    for v in vs))
                       for slot, vs in op.inputs.items())))
            kept = seen.get(key)
            if kept is not None:
                ok = True
                for slot, vs in op.outputs.items():
                    kvs = kept.outputs.get(slot, [])
                    if len(kvs) != len(vs):
                        ok = False
                        break
                if ok and set(op.outputs) == set(kept.outputs):
                    for slot, vs in op.outputs.items():
                        for dup_v, kept_v in zip(vs, kept.outputs[slot]):
                            alias[dup_v.name] = kept_v.name
                    merged_ops.add(id(op))
                    merged += 1
                    continue
            else:
                seen[key] = op
        # bump UNDECLARED sub-block writes too: a while body updating an
        # outer name the while op never lists as an output still changes
        # the value later reads see
        for n in _written_names(program, op, cache=bw_cache):
            version[n] = version.get(n, 0) + 1

    if not merged:
        report.note('cse', ops_merged=0)
        return 0

    block.ops = [op for op in block.ops if id(op) not in merged_ops]
    # redirect every read of a merged name (all blocks: sub-block bodies
    # read outer names) to the surviving producer's variable
    for blk in program.blocks:
        for op in blk.ops:
            for slot, vs in op.inputs.items():
                changed = False
                new_vs = []
                for v in vs:
                    tgt = resolve(v.name)
                    if tgt != v.name:
                        new_vs.append(block.vars.get(tgt) or
                                      blk._var_recursive(tgt))
                        changed = True
                    else:
                        new_vs.append(v)
                if changed:
                    op.inputs[slot] = new_vs
    program._bump_version()
    _C_MERGED.inc(merged)
    report.note('cse', ops_merged=merged)
    return merged
