"""Int8 weight quantization as an IR rewrite (docs/perf.md#quantized-inference).

Two surfaces over the same three ops (ops_impl/quant_ops.py):

* `run(program, report)` — the PASS-PIPELINE form, modeled on
  amp_pass.run and gated the same way (mark the program with
  `mark_quant`, let `optimize()` rewrite the clone). Every eligible op
  with a frozen float32 weight gets EXPLICIT quantize/dequantize ops:
  `mul`/`matmul` weights route through `quantize` -> `dequantize` (the
  reference's fake-quant form — the op still consumes f32, but every
  precision boundary is a real op `analysis`/provenance/`program_lint`
  can see, and CSE dedups repeated QDQ of the same weight version);
  `lookup_table` rewrites to `quant_lookup_table`, which gathers int8
  rows + per-row scales and dequantizes POST-gather.

* `quantize_weights(program, scope)` — the OFFLINE form for deployment:
  computes each weight's int8 tensor + per-channel scale eagerly
  (through quant_ops.quantize_array — one definition of the rounding),
  installs them as `W@quant.int8` / `W@quant.scale` persistables in the
  scope, repoints consumers (mul/matmul through a `dequantize` temp,
  lookup_table to `quant_lookup_table`), and DROPS the now-unreferenced
  f32 weight from the block — so save_inference_model ships int8 bytes
  and the Predictor's device upload halves (doubles vocab per HBM byte
  for row-quantized tables).

Numerics (the documented tolerance, drilled by tests/test_kernels.py):
symmetric per-channel int8 round-trip error is bounded by half a
quantization step per element — |deq(q(x)) - x| <= max|x[ch]| / 254 —
so a single quantized matmul/lookup deviates by at most that bound
times the reduction's L1 mass; everything outside rewritten ops is
bit-identical. Per-channel (not per-tensor) scales keep outlier
channels from poisoning the rest, the standard weight-only int8 recipe.
"""
from ... import obs
from ..framework import Operator
from . import OP_SEQ_ATTR

__all__ = ['mark_quant', 'is_quant', 'run', 'quantize_weights',
           'QUANT_SLOTS']

_C_REWRITTEN = obs.counter('passes.quant.ops_rewritten')
_C_QDQ = obs.counter('passes.quant.qdq_inserted')
_C_WEIGHTS = obs.counter('passes.quant.weights_quantized')

# op type -> (weight input slot, per-channel axis of that weight).
# Weight-only quantization: activations stay f32, so downstream dtypes
# never change and no abstract-eval eligibility probe is needed (unlike
# the amp rewrite). lookup_table's axis 0 is per-ROW (the embedding
# row-store layout embedding/quant_rows.py shares); matmul weights
# quantize per OUTPUT channel (axis 1 of [K, N]).
QUANT_SLOTS = {
    'mul': ('Y', 1),
    'matmul': ('Y', 1),
    'lookup_table': ('W', 0),
}


def mark_quant(program, ops=None, weight_dtype='int8'):
    """Arm the quant rewrite for this program (the amp.decorate_program
    idiom): optimize() will run the pass on its clone. `ops` optionally
    restricts rewriting to a subset of QUANT_SLOTS op types."""
    if weight_dtype != 'int8':
        raise ValueError('only int8 weight quantization is implemented, '
                         'got %r' % (weight_dtype,))
    program._quant = True
    if ops is not None:
        program._quant_ops = tuple(ops)
    program._bump_version()
    return program


def is_quant(program):
    return bool(getattr(program, '_quant', False))


def _quant_types(program):
    sel = getattr(program, '_quant_ops', None)
    return set(sel) if sel is not None else set(QUANT_SLOTS)


def _weight_target(block, op, types):
    """The (slot, axis, var) to quantize for `op`, or None: the weight
    slot's single input when it is a frozen f32 persistable."""
    if op.type not in types or op.type not in QUANT_SLOTS:
        return None
    slot, axis = QUANT_SLOTS[op.type]
    vs = op.inputs.get(slot)
    if not vs or len(vs) != 1:
        return None
    v = vs[0]
    if not getattr(v, 'persistable', False) or v.dtype != 'float32':
        return None
    return slot, axis, v


def _scale_shape(shape, axis):
    if shape is None:
        return None
    return [int(d) if i == axis else 1 for i, d in enumerate(shape)]


def run(program, report):
    """Rewrite eligible ops in place (program is optimize()'s clone).
    Returns the number of ops rewritten."""
    from . import written_names
    block = program.global_block()
    types = _quant_types(program)
    version = {}           # name -> write version (the block is not SSA)
    qdq_cache = {}         # (name, version) -> (q var, scale var, deq var)
    new_ops = []
    inserted = rewritten = 0
    bw_cache = {}

    def bump(op):
        for n in written_names(program, op, cache=bw_cache):
            version[n] = version.get(n, 0) + 1

    for op in block.ops:
        target = _weight_target(block, op, types)
        if target is None:
            new_ops.append(op)
            bump(op)
            continue
        slot, axis, v = target
        seq = op.attrs.get(OP_SEQ_ATTR, 0)
        callsite = getattr(op, 'callsite', None)
        ck = (v.name, version.get(v.name, 0))
        cached = qdq_cache.get(ck)
        if cached is None:
            qv = block.create_var(
                name='%s@quant.v%d.int8' % (v.name, ck[1]),
                shape=list(v.shape) if v.shape is not None else None,
                dtype='int8', lod_level=v.lod_level)
            sv = block.create_var(
                name='%s@quant.v%d.scale' % (v.name, ck[1]),
                shape=_scale_shape(v.shape, axis),
                dtype='float32', lod_level=0)
            new_ops.append(Operator(
                block, type='quantize', inputs={'X': [v]},
                outputs={'Out': [qv], 'Scale': [sv]},
                attrs={'axis': axis, OP_SEQ_ATTR: seq},
                callsite=callsite))
            cached = [qv, sv, None]
            qdq_cache[ck] = cached
            inserted += 1
        qv, sv, dv = cached
        if op.type == 'lookup_table':
            # gather stays int8-side: rewrite the op itself
            op.type = 'quant_lookup_table'
            op.inputs[slot] = [qv]
            op.inputs['Scale'] = [sv]
        else:
            if dv is None:
                dv = block.create_var(
                    name='%s@quant.v%d.deq' % (v.name, ck[1]),
                    shape=list(v.shape) if v.shape is not None else None,
                    dtype='float32', lod_level=v.lod_level)
                new_ops.append(Operator(
                    block, type='dequantize',
                    inputs={'X': [qv], 'Scale': [sv]},
                    outputs={'Out': [dv]},
                    attrs={OP_SEQ_ATTR: seq}, callsite=callsite))
                cached[2] = dv
                inserted += 1
            op.inputs[slot] = [dv]
        new_ops.append(op)
        bump(op)
        rewritten += 1

    if rewritten or inserted:
        block.ops = new_ops
        program._bump_version()
        _C_REWRITTEN.inc(rewritten)
        _C_QDQ.inc(inserted)
    # quant becomes an IR property of the rewritten clone, exactly the
    # amp pass's flag protocol
    program._quant = False
    program._quant_ir = True
    report.note('quant', ops_rewritten=rewritten, qdq_inserted=inserted)
    return rewritten


def quantize_weights(program, scope, ops=None):
    """Offline weight quantization for deployment (see module
    docstring). Mutates `program` and `scope` in place; returns the
    number of weights quantized. Run on the pruned inference clone
    BEFORE save_inference_model so the artifact ships int8 bytes."""
    import jax.numpy as jnp
    import numpy as np
    from ..ops_impl.quant_ops import quantize_array

    block = program.global_block()
    types = set(ops) if ops is not None else set(QUANT_SLOTS)
    made = {}              # weight name -> (q var, scale var)
    replaced = set()
    new_ops = []
    quantized = 0

    for op in block.ops:
        target = _weight_target(block, op, types)
        if target is None:
            new_ops.append(op)
            continue
        slot, axis, v = target
        val = scope.vars.get(v.name)
        if val is None:
            new_ops.append(op)
            continue
        if v.name not in made:
            q, scale = quantize_array(jnp.asarray(np.asarray(val)),
                                      axis=axis)
            qv = block.create_var(
                name=v.name + '@quant.int8',
                shape=list(v.shape) if v.shape is not None else None,
                dtype='int8', lod_level=v.lod_level, persistable=True)
            sv = block.create_var(
                name=v.name + '@quant.scale',
                shape=_scale_shape(v.shape, axis),
                dtype='float32', persistable=True)
            scope.vars[qv.name] = q
            scope.vars[sv.name] = scale
            made[v.name] = (qv, sv)
            quantized += 1
        qv, sv = made[v.name]
        seq = op.attrs.get(OP_SEQ_ATTR, 0) if OP_SEQ_ATTR in op.attrs \
            else None
        if op.type == 'lookup_table':
            op.type = 'quant_lookup_table'
            op.inputs[slot] = [qv]
            op.inputs['Scale'] = [sv]
        else:
            dv = block.vars.get(v.name + '@quant.deq')
            if dv is None:
                dv = block.create_var(
                    name=v.name + '@quant.deq',
                    shape=list(v.shape) if v.shape is not None else None,
                    dtype='float32', lod_level=v.lod_level)
                attrs = {} if seq is None else {OP_SEQ_ATTR: seq}
                new_ops.append(Operator(
                    block, type='dequantize',
                    inputs={'X': [qv], 'Scale': [sv]},
                    outputs={'Out': [dv]},
                    attrs=attrs, callsite=getattr(op, 'callsite', None)))
            op.inputs[slot] = [dv]
        new_ops.append(op)
        replaced.add(v.name)

    if not quantized:
        return 0
    block.ops = new_ops
    # drop f32 weights no block still references: save_inference_model
    # then skips their bytes and the executor never uploads them
    still_used = set()
    for blk in program.blocks:
        for op in blk.ops:
            for n in op.input_arg_names:
                still_used.add(n)
            for n in op.output_arg_names:
                still_used.add(n)
    for name in replaced:
        if name not in still_used and name in block.vars:
            del block.vars[name]
    program._quant_ir = True
    program._bump_version()
    _C_WEIGHTS.inc(quantized)
    return quantized
