"""API annotations. Parity: reference python/paddle/fluid/annotations.py
(the `deprecated` decorator used across the fluid API surface)."""
import functools
import sys

__all__ = ['deprecated']


def deprecated(since, instead, extra_message=""):
    """Mark an API as deprecated since version `since`; point at `instead`."""
    def decorator(func):
        err_msg = "API {0} is deprecated since {1}. Please use {2} instead.".format(
            func.__name__, since, instead)
        if extra_message:
            err_msg += "\n" + extra_message

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            print(err_msg, file=sys.stderr)
            return func(*args, **kwargs)

        wrapper.__doc__ = (("\n\nWarning: " + err_msg + "\n")
                           + (func.__doc__ or ""))
        return wrapper
    return decorator
