"""Optimizers: append per-param update ops after backward.

Parity: reference python/paddle/fluid/optimizer.py. The update ops lower
into the same fused XLA step as forward+backward (see executor.py).
"""
from collections import defaultdict

from . import framework
from . import unique_name
from .framework import Variable, Parameter, default_main_program, \
    default_startup_program, program_guard, ROLE_OPTIMIZE
from .backward import append_backward
from .clip import append_gradient_clip_ops, ErrorClipByValue
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops
from .layers import tensor as tensor_layers

__all__ = [
    'SGD', 'Momentum', 'Adagrad', 'Adam', 'Adamax', 'DecayedAdagrad', 'Ftrl',
    'SGDOptimizer', 'MomentumOptimizer', 'AdagradOptimizer', 'AdamOptimizer',
    'AdamaxOptimizer', 'DecayedAdagradOptimizer', 'RMSPropOptimizer',
    'FtrlOptimizer', 'Adadelta', 'AdadeltaOptimizer', 'ModelAverage',
    'Optimizer',
]


class Optimizer(object):
    """Base optimizer (reference optimizer.py:Optimizer)."""

    def __init__(self, learning_rate, regularization=None, LARS_weight_decay=0.0):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError("learning rate should be float or Variable")
        self.regularization = regularization
        self._learning_rate = learning_rate
        self._learning_rate_map = dict()
        self._accumulators = defaultdict(lambda: dict())
        self.helper = None
        self._LARS_weight_decay = LARS_weight_decay

    def _create_global_learning_rate(self):
        lr = self._global_learning_rate()
        if isinstance(lr, Variable):
            return
        if isinstance(self._learning_rate, Variable):
            # scheduled lr (a Variable computed by lr_scheduler ops)
            self._learning_rate_map[default_main_program()] = \
                self._learning_rate
            return
        if not isinstance(self._learning_rate, float):
            raise TypeError("learning rate should be float or Variable")
        self._learning_rate_map[default_main_program()] = \
            tensor_layers.create_global_var(
                name=unique_name.generate("learning_rate"),
                shape=[1], value=float(self._learning_rate),
                dtype='float32', persistable=True)

    def _global_learning_rate(self, program=None):
        if program is None:
            program = default_main_program()
        return self._learning_rate_map.get(program, None)

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError()

    def _create_param_lr(self, param_and_grad):
        param_lr = param_and_grad[0].optimize_attr['learning_rate']
        if param_lr == 1.0:
            return self._global_learning_rate()
        from .layers import ops as ops_layers
        return ops_layers.scale(self._global_learning_rate(),
                                scale=float(param_lr))

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block):
        pass

    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            raise Exception("Accumulator %s already exists for %s" %
                            (name, param.name))
        if shape is None:
            shape = list(param.shape)
        assert isinstance(self.helper, LayerHelper)
        var = self.helper.create_global_variable(
            name=unique_name.generate(name + "_" + param.name),
            persistable=True, dtype=dtype or param.dtype, shape=shape)
        # marks ZeRO-shardable state for the distribute path (executor.py)
        var._is_optimizer_accumulator = True
        # same-shape accumulators inherit their master parameter's GSPMD
        # annotation (docs/parallel.md): adam moments of a row-sharded
        # embedding table are themselves vocab-sized — replicating them
        # would forfeit the memory scaling the annotation asked for
        # (docs/embedding.md; the legacy dist path does the same by
        # name-matching tp specs). Scalar state (beta pows) passes a
        # `shape` of its own and stays replicated.
        if (getattr(param, 'sharding', None) is not None
                and list(shape) == list(param.shape)):
            var.sharding = param.sharding
            var._annot_callsite = getattr(param, '_annot_callsite', None)
        self._accumulators[name][param.name] = var
        self.helper.set_variable_initializer(
            var, initializer=Constant(value=float(fill_value)))
        return var

    def _get_accumulator(self, name, param):
        if name not in self._accumulators or \
                param.name not in self._accumulators[name]:
            raise Exception("Accumulator %s does not exist for %s" %
                            (name, param.name))
        return self._accumulators[name][param.name]

    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        """reference optimizer.py:create_optimization_pass."""
        program = loss.block.program
        with program_guard(program, startup_program):
            self.helper = LayerHelper(self.__class__.__name__)
            self._create_accumulators(
                loss.block, [p[0] for p in parameters_and_grads if p[0].trainable])
            self._create_global_learning_rate()

            optimize_ops = []
            for param_and_grad in parameters_and_grads:
                if param_and_grad[1] is None:
                    continue
                if param_and_grad[0].trainable:
                    op = self._append_optimize_op(loss.block, param_and_grad)
                    op.attrs['op_role'] = ROLE_OPTIMIZE
                    optimize_ops.append(op)
            self._finish_update(loss.block)
            return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """reference optimizer.py:Optimizer.minimize."""
        params_grads = append_backward(loss, parameter_list, no_grad_set)
        params_grads = sorted(params_grads, key=lambda x: x[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        optimize_ops = self._create_optimization_pass(params_grads, loss,
                                                      startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, **kwargs):
        super(SGDOptimizer, self).__init__(learning_rate=learning_rate, **kwargs)
        self.type = "sgd"

    def _append_optimize_op(self, block, param_and_grad):
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0]}, infer_shape=False)


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = "velocity"

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kwargs):
        super(MomentumOptimizer, self).__init__(learning_rate=learning_rate,
                                                **kwargs)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        velocity_acc = self._get_accumulator(self._velocity_acc_str,
                                             param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "Velocity": velocity_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0],
                     "VelocityOut": velocity_acc},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
            infer_shape=False)


class AdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, epsilon=1.0e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate=learning_rate,
                                               **kwargs)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "Moment": moment_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0], "MomentOut": moment_acc},
            attrs={"epsilon": self._epsilon}, infer_shape=False)


class AdamOptimizer(Optimizer):
    _moment1_acc_str = "moment1"
    _moment2_acc_str = "moment2"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamOptimizer, self).__init__(learning_rate=learning_rate,
                                            **kwargs)
        self.type = "adam"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        main_block = block.program.global_block()
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta1_pow_acc'), dtype='float32',
            shape=[1], persistable=True)
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1))
        self._beta2_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta2_pow_acc'), dtype='float32',
            shape=[1], persistable=True)
        self.helper.set_variable_initializer(
            self._beta2_pow_acc, initializer=Constant(self._beta2))
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment1 = self._get_accumulator(self._moment1_acc_str,
                                        param_and_grad[0])
        moment2 = self._get_accumulator(self._moment2_acc_str,
                                        param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "LearningRate": self._create_param_lr(param_and_grad),
                    "Moment1": moment1, "Moment2": moment2,
                    "Beta1Pow": self._beta1_pow_acc,
                    "Beta2Pow": self._beta2_pow_acc},
            outputs={"ParamOut": param_and_grad[0], "Moment1Out": moment1,
                     "Moment2Out": moment2},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)

    def _finish_update(self, block):
        """Update beta1^t / beta2^t once per step (reference appends scale
        ops in a with-block)."""
        block.append_op(
            type="adam_beta_pow_update",
            inputs={"Beta1Pow": self._beta1_pow_acc,
                    "Beta2Pow": self._beta2_pow_acc},
            outputs={"Beta1PowOut": self._beta1_pow_acc,
                     "Beta2PowOut": self._beta2_pow_acc},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "op_role": ROLE_OPTIMIZE},
            infer_shape=False)


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = "moment"
    _inf_norm_acc_str = "inf_norm"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamaxOptimizer, self).__init__(learning_rate=learning_rate,
                                              **kwargs)
        self.type = "adamax"
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        self._beta1_pow_acc = self.helper.create_global_variable(
            name=unique_name.generate('beta1_pow_acc'), dtype='float32',
            shape=[1], persistable=True)
        self.helper.set_variable_initializer(
            self._beta1_pow_acc, initializer=Constant(self._beta1))
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment = self._get_accumulator(self._moment_acc_str, param_and_grad[0])
        inf_norm = self._get_accumulator(self._inf_norm_acc_str,
                                         param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "LearningRate": self._create_param_lr(param_and_grad),
                    "Moment": moment, "InfNorm": inf_norm,
                    "Beta1Pow": self._beta1_pow_acc},
            outputs={"ParamOut": param_and_grad[0], "MomentOut": moment,
                     "InfNormOut": inf_norm},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon}, infer_shape=False)

    def _finish_update(self, block):
        block.append_op(
            type="scale",
            inputs={"X": self._beta1_pow_acc},
            outputs={"Out": self._beta1_pow_acc},
            attrs={"scale": self._beta1, "op_role": ROLE_OPTIMIZE},
            infer_shape=False)


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = "moment"

    def __init__(self, learning_rate, decay=0.95, epsilon=1.0e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(
            learning_rate=learning_rate, **kwargs)
        self.type = "decayed_adagrad"
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        moment_acc = self._get_accumulator(self._moment_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "Moment": moment_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0], "MomentOut": moment_acc},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
            infer_shape=False)


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = "_avg_squared_grad"
    _avg_squared_update_acc_str = "_avg_squared_update"

    def __init__(self, learning_rate, epsilon=1.0e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate=learning_rate,
                                                **kwargs)
        self.type = "adadelta"
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        avg_squared_grad_acc = self._get_accumulator(
            self._avg_squared_grad_acc_str, param_and_grad[0])
        avg_squared_update_acc = self._get_accumulator(
            self._avg_squared_update_acc_str, param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "AvgSquaredGrad": avg_squared_grad_acc,
                    "AvgSquaredUpdate": avg_squared_update_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0],
                     "AvgSquaredGradOut": avg_squared_grad_acc,
                     "AvgSquaredUpdateOut": avg_squared_update_acc},
            attrs={"epsilon": self._epsilon, "rho": self._rho},
            infer_shape=False)


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = "momentum"
    _mean_square_acc_str = "mean_square"

    def __init__(self, learning_rate, rho=0.95, epsilon=1.0e-6, momentum=0.0,
                 **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate=learning_rate,
                                               **kwargs)
        self.type = "rmsprop"
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        momentum_acc = self._get_accumulator(self._momentum_acc_str,
                                             param_and_grad[0])
        mean_square_acc = self._get_accumulator(self._mean_square_acc_str,
                                                param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "Moment": momentum_acc, "MeanSquare": mean_square_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0],
                     "MomentOut": momentum_acc,
                     "MeanSquareOut": mean_square_acc},
            attrs={"epsilon": self._epsilon, "decay": self._rho,
                   "momentum": self._momentum}, infer_shape=False)


class FtrlOptimizer(Optimizer):
    _squared_acc_str = "squared"
    _linear_acc_str = "linear"

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate=learning_rate,
                                            **kwargs)
        self.type = "ftrl"
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        squared_acc = self._get_accumulator(self._squared_acc_str,
                                            param_and_grad[0])
        linear_acc = self._get_accumulator(self._linear_acc_str,
                                           param_and_grad[0])
        return block.append_op(
            type=self.type,
            inputs={"Param": param_and_grad[0], "Grad": param_and_grad[1],
                    "SquaredAccumulator": squared_acc,
                    "LinearAccumulator": linear_acc,
                    "LearningRate": self._create_param_lr(param_and_grad)},
            outputs={"ParamOut": param_and_grad[0],
                     "SquaredAccumOut": squared_acc,
                     "LinearAccumOut": linear_acc},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
            infer_shape=False)


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer


class ModelAverage(Optimizer):
    """Moving average of parameters for evaluation
    (reference optimizer.py:ModelAverage). Accumulates sums of params each
    step; apply()/restore() swap averaged params in and out of the scope."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kwargs):
        super(ModelAverage, self).__init__(0.0, **kwargs)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads = []
        self._sums = {}
        self._num_acc = 0
        self._backup = {}
        main = default_main_program()
        for param in main.global_block().all_parameters():
            if param.do_model_average is not False:
                self.params_grads.append((param, None))

    def _append_average_accumulate_op(self, param):
        pass  # accumulation is host-side below (no graph mutation needed)

    def accumulate(self, executor=None):
        """Call once per trained batch (host-side running sum)."""
        import numpy as np
        from .executor import global_scope
        scope = global_scope()
        for param, _ in self.params_grads:
            v = scope.vars.get(param.name)
            if v is None:
                continue
            a = np.asarray(v)
            if param.name in self._sums:
                self._sums[param.name] += a
            else:
                self._sums[param.name] = a.copy()
        self._num_acc += 1

    import contextlib

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import numpy as np
        import jax.numpy as jnp
        from .executor import global_scope
        scope = global_scope()
        self._backup = {}
        for param, _ in self.params_grads:
            if param.name in self._sums and self._num_acc > 0:
                self._backup[param.name] = scope.vars[param.name]
                scope.vars[param.name] = jnp.asarray(
                    self._sums[param.name] / float(self._num_acc))
        yield
        if need_restore:
            self.restore(executor)

    def restore(self, executor=None):
        from .executor import global_scope
        scope = global_scope()
        for name, v in self._backup.items():
            scope.vars[name] = v
        self._backup = {}
