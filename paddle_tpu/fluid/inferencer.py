"""High-level Inferencer API. Parity: reference python/paddle/fluid/
inferencer.py:31 — builds the inference program from infer_func, loads
params saved by Trainer.save_params, and runs feeds through the Executor
(one jitted XLA module per feed signature)."""
import contextlib

from . import framework
from . import io
from . import parallel_executor
from . import unique_name
from .executor import Executor, Scope, scope_guard
from .trainer import check_and_get_place

__all__ = ['Inferencer']


class Inferencer(object):
    """reference inferencer.py:31."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        self.param_path = param_path
        self.scope = Scope()
        self.parallel = parallel
        self.place = check_and_get_place(place)

        self.inference_program = framework.Program()
        with framework.program_guard(self.inference_program):
            with unique_name.guard():
                self.predict_var = infer_func()

        with self._prog_and_scope_guard():
            io.load_params(Executor(self.place), param_path,
                           main_program=self.inference_program)

        self.inference_program = self.inference_program.clone(for_test=True)

        if parallel:
            with self._prog_and_scope_guard():
                self.exe = parallel_executor.ParallelExecutor(
                    use_cuda=False, loss_name=self.predict_var.name,
                    main_program=self.inference_program, scope=self.scope)
        else:
            self.exe = Executor(self.place)

    def infer(self, inputs, return_numpy=True):
        """reference inferencer.py:79."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        with scope_guard(self.scope):
            if self.parallel:
                return self.exe.run([self.predict_var.name], feed=inputs,
                                    return_numpy=return_numpy)
            return self.exe.run(self.inference_program, feed=inputs,
                                fetch_list=[self.predict_var],
                                return_numpy=return_numpy)

    @contextlib.contextmanager
    def _prog_and_scope_guard(self):
        with framework.program_guard(main_program=self.inference_program):
            with scope_guard(self.scope):
                yield
