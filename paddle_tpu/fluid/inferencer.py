"""High-level Inferencer API. Parity: reference python/paddle/fluid/
inferencer.py:31 — builds the inference program from infer_func, loads
params saved by Trainer.save_params, and runs feeds through the Executor
(one jitted XLA module per feed signature).

There is ONE inference execution path: the Executor with this
Inferencer's private scope passed explicitly (the same contract as
paddle_tpu.inference.Predictor — no global scope_guard on the run path,
so inferencers are thread-safe). The reference's `parallel=True`
ParallelExecutor branch is retired: on TPU a single-feed inference step
gains nothing from the dp mesh, and batched/concurrent serving belongs
to paddle_tpu.serving (docs/serving.md, docs/migration.md).
"""
from . import framework
from . import io
from . import unique_name
from .executor import Executor, Scope
from .trainer import check_and_get_place

__all__ = ['Inferencer']


class Inferencer(object):
    """reference inferencer.py:31."""

    def __init__(self, infer_func, param_path, place=None, parallel=False):
        if parallel:
            import warnings
            warnings.warn(
                'Inferencer(parallel=True) is deprecated and ignored: '
                'inference runs through the single Executor path; for '
                'high-throughput concurrent inference use '
                'paddle_tpu.serving.ServingEngine (docs/serving.md)',
                DeprecationWarning, stacklevel=2)
        self.param_path = param_path
        self.scope = Scope()
        self.place = check_and_get_place(place)

        self.inference_program = framework.Program()
        with framework.program_guard(self.inference_program):
            with unique_name.guard():
                self.predict_var = infer_func()

        self.exe = Executor(self.place)
        io.load_params(self.exe, param_path,
                       main_program=self.inference_program, scope=self.scope)

        self.inference_program = self.inference_program.clone(for_test=True)

    def infer(self, inputs, return_numpy=True):
        """reference inferencer.py:79."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        return self.exe.run(self.inference_program, feed=inputs,
                            fetch_list=[self.predict_var],
                            return_numpy=return_numpy, scope=self.scope)
