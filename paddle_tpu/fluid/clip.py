"""Gradient / error clipping.

Parity: reference python/paddle/fluid/clip.py.
"""
import copy

from . import framework

__all__ = ['ErrorClipByValue', 'GradientClipByValue', 'GradientClipByNorm',
           'GradientClipByGlobalNorm', 'set_gradient_clip',
           'append_gradient_clip_ops']


class BaseErrorClipAttr(object):
    def _append_clip_op(self, block, grad_name):
        raise NotImplementedError


class ErrorClipByValue(BaseErrorClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _append_clip_op(self, block, grad_name):
        var = block._var_recursive(grad_name)
        block.append_op(type='clip', inputs={'X': var}, outputs={'Out': var},
                        attrs={'min': self.min, 'max': self.max,
                               'op_role': framework.ROLE_BACKWARD},
                        infer_shape=False)


class BaseGradientClipAttr(object):
    def _process_context(self, context, param, grad):
        pass

    def _create_operators(self, param, grad):
        raise NotImplementedError


class NullGradientClipAttr(BaseGradientClipAttr):
    def _create_operators(self, param, grad):
        return param, grad


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        max = float(max)
        self.max = max
        self.min = float(min) if min is not None else -max

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape)
        block.append_op(type='clip', inputs={'X': grad}, outputs={'Out': out},
                        attrs={'min': self.min, 'max': self.max,
                               'op_role': framework.ROLE_BACKWARD})
        return param, out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _create_operators(self, param, grad):
        block = grad.block
        out = block.create_var(dtype=grad.dtype, shape=grad.shape)
        block.append_op(type='clip_by_norm', inputs={'X': grad},
                        outputs={'Out': out},
                        attrs={'max_norm': self.clip_norm,
                               'op_role': framework.ROLE_BACKWARD})
        return param, out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """reference clip.py GradientClipByGlobalNorm: scale all grads by
    clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm, group_name='default_group'):
        self.clip_norm = clip_norm
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        ctx = context.setdefault(self.group_name,
                                 {'grads': [], 'clip_norm': self.clip_norm})
        ctx['grads'].append((param, grad))

    def _create_operators(self, param, grad):
        return param, grad  # actual ops emitted by append_gradient_clip_ops

    @staticmethod
    def _emit_group(ctx):
        from .layers import nn, tensor, ops
        pgs = ctx['grads']
        block = pgs[0][1].block
        sq_sums = []
        for _, g in pgs:
            sq = block.create_var(dtype=g.dtype)
            block.append_op(type='square', inputs={'X': g}, outputs={'Out': sq},
                            attrs={'op_role': framework.ROLE_BACKWARD})
            red = block.create_var(dtype=g.dtype)
            block.append_op(type='reduce_sum', inputs={'X': sq},
                            outputs={'Out': red},
                            attrs={'reduce_all': True,
                                   'op_role': framework.ROLE_BACKWARD})
            sq_sums.append(red)
        gsum = block.create_var(dtype=sq_sums[0].dtype)
        block.append_op(type='sum', inputs={'X': sq_sums}, outputs={'Out': gsum},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        gnorm = block.create_var(dtype=gsum.dtype)
        block.append_op(type='sqrt', inputs={'X': gsum}, outputs={'Out': gnorm},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        clip_c = block.create_var(dtype=gnorm.dtype)
        block.append_op(type='fill_constant', outputs={'Out': clip_c},
                        attrs={'shape': [], 'dtype': 'float32',
                               'value': float(ctx['clip_norm']),
                               'op_role': framework.ROLE_BACKWARD},
                        infer_shape=False)
        denom = block.create_var(dtype=gnorm.dtype)
        block.append_op(type='elementwise_max', inputs={'X': gnorm, 'Y': clip_c},
                        outputs={'Out': denom},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        scale = block.create_var(dtype=gnorm.dtype)
        block.append_op(type='elementwise_div', inputs={'X': clip_c, 'Y': denom},
                        outputs={'Out': scale},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        outs = []
        for p, g in pgs:
            ng = g.block.create_var(dtype=g.dtype, shape=g.shape)
            g.block.append_op(type='elementwise_mul', inputs={'X': g, 'Y': scale},
                              outputs={'Out': ng},
                              attrs={'op_role': framework.ROLE_BACKWARD})
            outs.append((p, ng))
        return outs


def set_gradient_clip(clip, param_list=None, program=None):
    """reference clip.py:set_gradient_clip."""
    if not isinstance(clip, BaseGradientClipAttr):
        raise TypeError("clip should be BaseGradientClipAttr")
    if program is None:
        program = framework.default_main_program()
    if param_list is None:
        param_list = program.global_block().all_parameters()
    if all(isinstance(elem, str) for elem in param_list):
        param_list = [framework.get_var(name, program) for name in param_list]
    for param in param_list:
        param.gradient_clip_attr = copy.deepcopy(clip)


def append_gradient_clip_ops(param_grad):
    context = {}
    clips = []
    for p, g in param_grad:
        clip_attr = getattr(p, 'gradient_clip_attr', None) or NullGradientClipAttr()
        clips.append(clip_attr)
        clip_attr._process_context(context, p, g)
    res = []
    global_groups = {}
    for (p, g), clip_attr in zip(param_grad, clips):
        if isinstance(clip_attr, GradientClipByGlobalNorm):
            global_groups.setdefault(clip_attr.group_name, []).append((p, g))
        else:
            res.append(clip_attr._create_operators(p, g))
    for name, pgs in global_groups.items():
        ctx = context[name]
        res.extend(GradientClipByGlobalNorm._emit_group(ctx))
    return res
