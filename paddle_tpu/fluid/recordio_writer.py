"""Convert Python readers into recordio files.

Parity: reference python/paddle/fluid/recordio_writer.py
(convert_reader_to_recordio_file / _files over a DataFeeder). Backed by
the C++ chunked record writer (csrc/recordio.cpp, with a pure-python
fallback) instead of the reference's core.RecordIOWriter; each record
packs one batch's feed tensors (npz, pickle-free) in feed_order.
"""
import contextlib

import numpy as np

from ..reader import recordio as _rio

__all__ = [
    'convert_reader_to_recordio_file', 'convert_reader_to_recordio_files',
    'unpack_feed_record'
]


@contextlib.contextmanager
def create_recordio_writer(filename, compressor=None, max_num_records=1000):
    writer = _rio.RecordIOWriter(filename)
    try:
        yield writer
    finally:
        writer.close()


def _append_batch(writer, res, feed_order):
    """Pack one batch self-describingly: a leading int64 schema array
    [n_slots, lod_levels_per_slot...], then per slot the (flattened,
    unpadded) data array followed by one lengths array per LoD level —
    sequence structure survives the round-trip (the reference writes the
    LoDTensor's lod table the same way)."""
    from .lod_tensor import LoDTensor
    from .lowering import SeqValue
    arrays = [None]  # schema placeholder
    schema = []
    for name in feed_order:
        v = res[name]
        if isinstance(v, SeqValue):
            v = LoDTensor.from_seq_value(v)
        if isinstance(v, LoDTensor) and v.recursive_sequence_lengths():
            levels = v.recursive_sequence_lengths()
            schema.append(len(levels))
            arrays.append(np.asarray(v.data))
            arrays.extend(np.asarray(lv, np.int64) for lv in levels)
        else:
            schema.append(0)
            arrays.append(np.asarray(getattr(v, 'data', v)))
    arrays[0] = np.asarray([len(feed_order)] + schema, np.int64)
    writer.write(_rio._pack_sample(arrays))


def unpack_feed_record(payload):
    """Inverse of the record layout written here: returns one value per
    feed slot — a plain ndarray, or a LoDTensor when the slot carried
    sequence structure."""
    from .lod_tensor import LoDTensor
    arrs = list(_rio._unpack_sample(payload))
    schema = arrs[0]
    n_slots = int(schema[0])
    out = []
    i = 1
    for s in range(n_slots):
        levels = int(schema[1 + s])
        data = arrs[i]
        i += 1
        if levels == 0:
            out.append(data)
        else:
            lens = [[int(x) for x in arrs[i + j]] for j in range(levels)]
            i += levels
            out.append(LoDTensor(np.asarray(data), lens))
    return out


def convert_reader_to_recordio_file(filename, reader_creator, feeder,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None):
    """Write every batch of `reader_creator` through `feeder` into one
    recordio file; returns the number of records written."""
    if feed_order is None:
        feed_order = feeder.feed_names
    counter = 0
    with create_recordio_writer(filename, compressor,
                                max_num_records) as writer:
        for batch in reader_creator():
            res = feeder.feed(batch)
            _append_batch(writer, res, feed_order)
            counter += 1
    return counter


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None):
    """Same as convert_reader_to_recordio_file but splits the stream into
    files of at most `batch_per_file` records (filename-00000, -00001, ...);
    returns the total number of records."""
    if feed_order is None:
        feed_order = feeder.feed_names
    f_name, f_ext = filename, ''
    if '.' in filename.rsplit('/', 1)[-1]:
        f_name, f_ext = filename.rsplit('.', 1)
        f_ext = '.' + f_ext
    lines = 0
    f_idx = 0
    counter = 0
    writer = None
    try:
        for batch in reader_creator():
            if writer is None or lines == batch_per_file:
                if writer is not None:
                    writer.close()
                writer = _rio.RecordIOWriter(
                    '%s-%05d%s' % (f_name, f_idx, f_ext))
                f_idx += 1
                lines = 0
            res = feeder.feed(batch)
            _append_batch(writer, res, feed_order)
            lines += 1
            counter += 1
    finally:
        if writer is not None:
            writer.close()
    return counter
