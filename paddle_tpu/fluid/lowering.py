"""Op lowering registry: Fluid op symbols -> JAX.

TPU-first replacement for the reference's per-op C++/CUDA kernel registry
(paddle/fluid/framework/op_registry.h + operators/*_op.cu). Instead of a
kernel per (op, Place, dtype), each op type has ONE pure-JAX rule. The
Executor symbolically evaluates a whole Program through these rules inside a
single jax.jit trace, so XLA sees the entire training step as one module and
fuses across op boundaries (the reference pays a kernel launch per op).

The same rules power build-time shape inference via jax.eval_shape
(framework.Block.append_op), so op semantics are defined exactly once.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp

from .framework import DYN_DIM

_RULES = {}
_BLOCK_RULES = {}


class NoRuleError(KeyError):
    pass


def register(op_type):
    def deco(fn):
        _RULES[op_type] = fn
        return fn
    return deco


def register_block_op(op_type):
    """Register a structured-control-flow rule.

    Unlike plain rules (ins, attrs, ctx) -> outs, a block rule receives
    (op, env, ctx) and mutates env: it must execute its sub-block(s) itself
    (via run_block) under lax.while_loop / lax.scan / predicated select.
    This replaces the reference's C++ WhileOp/ConditionalBlockOp sub-scope
    interpreters (paddle/fluid/operators/while_op.cc,
    conditional_block_op.cc) with XLA-native structured control flow.
    """
    def deco(fn):
        _BLOCK_RULES[op_type] = fn
        return fn
    return deco


def get_rule(op_type):
    try:
        return _RULES[op_type]
    except KeyError:
        raise NoRuleError("no lowering rule for op %r" % op_type)


def has_rule(op_type):
    return op_type in _RULES


class Ctx(object):
    """Per-op lowering context: PRNG key, run mode, target platform
    (the Executor's Place decides this — jax.default_backend() lies when a
    TPU plugin is present but the computation is placed on CPU), and the
    device mesh the step is compiled against (None = single device) so
    mesh-aware rules (moe_mlp) can shard_map over it. `manual_axes` names
    mesh axes the op is ALREADY manual over (inside a shard_map body, e.g.
    the pipeline region): rules that would otherwise open their own
    shard_map (sp attention) must instead use the per-shard collective
    bodies on those axes."""

    __slots__ = ('key', 'op_index', 'is_test', 'amp', 'platform', 'mesh',
                 'manual_axes')

    def __init__(self, key, op_index=0, is_test=False, amp=False,
                 platform='cpu', mesh=None, manual_axes=frozenset()):
        self.key = key
        self.op_index = op_index
        self.is_test = is_test
        self.amp = amp
        self.platform = platform
        self.mesh = mesh
        self.manual_axes = manual_axes

    def rng(self):
        return jax.random.fold_in(self.key, self.op_index)


def amp_cast(ctx, *xs):
    """Under AMP, cast fp32 matmul/conv operands to bf16 for the MXU."""
    if not ctx.amp:
        return xs if len(xs) > 1 else xs[0]
    out = tuple(x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x
                for x in xs)
    return out if len(out) > 1 else out[0]


class SeqValue(object):
    """Runtime value of a lod_level>0 Variable: dense padded data + lengths.

    TPU-first replacement for LoDTensor's flattened [total_tokens, d] layout
    (reference paddle/fluid/framework/lod_tensor.h): static shapes
    [batch, max_len, ...] keep XLA happy; `lengths` int32[batch] carries the
    ragged structure of the INNERMOST LoD level; masked ops consult it.
    Nested LoD of arbitrary depth (the reference's recursive LoD table)
    keeps every level above the innermost in `outer_lengths`, a tuple of
    int32 vectors ordered outermost-first: level k's entries are lengths
    measured in units of level k+1's sequences, and the innermost level
    (`lengths`) is measured in tokens/rows. A bare array is accepted for
    the common 2-level case and normalised to a 1-tuple.
    """

    __slots__ = ('data', 'lengths', 'outer_lengths')

    def __init__(self, data, lengths, outer_lengths=None):
        self.data = data
        self.lengths = lengths
        if outer_lengths is not None and not isinstance(outer_lengths, tuple):
            if isinstance(outer_lengths, list):
                outer_lengths = tuple(outer_lengths)
            else:
                outer_lengths = (outer_lengths,)
        self.outer_lengths = outer_lengths or None

    @property
    def max_len(self):
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32):
        """[batch, max_len] validity mask."""
        t = self.data.shape[1]
        return (jnp.arange(t)[None, :] < self.lengths[:, None]).astype(dtype)

    def tree_flatten(self):
        if self.outer_lengths is None:
            return (self.data, self.lengths), 0
        return (self.data, self.lengths) + self.outer_lengths, \
            len(self.outer_lengths)

    @classmethod
    def tree_unflatten(cls, n_outer, children):
        if n_outer:
            return cls(children[0], children[1], tuple(children[2:2 + n_outer]))
        return cls(children[0], children[1])


jax.tree_util.register_pytree_node(
    SeqValue,
    lambda s: s.tree_flatten(),
    lambda aux, ch: SeqValue.tree_unflatten(aux, ch))


def data_of(v):
    return v.data if isinstance(v, SeqValue) else v


def like(template, new_data):
    """Wrap new_data with template's sequence structure (if any)."""
    if isinstance(template, SeqValue):
        return SeqValue(new_data, template.lengths, template.outer_lengths)
    return new_data


def first_seq(*vals):
    for v in vals:
        if isinstance(v, SeqValue):
            return v
    return None


def run_op(op, env, ctx):
    """Resolve an op's inputs from env, apply its rule, bind outputs."""
    if op.type in _BLOCK_RULES:
        _BLOCK_RULES[op.type](op, env, ctx)
        return
    rule = get_rule(op.type)
    ins = {slot: [env[v.name] for v in vs] for slot, vs in op.inputs.items()}
    outs = rule(ins, op.attrs, ctx)
    _bind_outputs(op, outs, env)


def run_block(block, env, ctx):
    """Execute every op of a (sub-)block against env, in place.

    The PRNG stream stays distinct per (block, op) position so dropout etc.
    inside loop bodies doesn't collide with the outer ops' streams.
    """
    base = block.idx * 4096
    for i, op in enumerate(block.ops):
        run_op(op, env, Ctx(ctx.key, base + i, is_test=ctx.is_test,
                            amp=ctx.amp, platform=ctx.platform,
                            mesh=ctx.mesh, manual_axes=ctx.manual_axes))


# Default slot count for LoDTensorArray buffers (see ArrayValue). Layers
# read layers/control_flow.py:ARRAY_CAPACITY (initialized from this) at
# call time; this is the single fallback for ops lacking a capacity attr.
DEFAULT_ARRAY_CAPACITY = 128


class ArrayValue(object):
    """Runtime value of a LOD_TENSOR_ARRAY variable.

    The reference's LoDTensorArray is a C++ vector<LoDTensor> grown by
    array_write ops inside While loops (operators/array_write_op.cc). Under
    XLA everything must be statically shaped, so an array is a preallocated
    ring of `capacity` slots [capacity, *elem] plus a live-length scalar;
    writes are lax.dynamic_update_slice, reads dynamic_index_in_dim. This
    makes arrays legal lax.while_loop carries.
    """

    __slots__ = ('buffer', 'length')

    def __init__(self, buffer, length):
        self.buffer = buffer
        self.length = length


jax.tree_util.register_pytree_node(
    ArrayValue,
    lambda a: ((a.buffer, a.length), None),
    lambda aux, ch: ArrayValue(ch[0], ch[1]))


def _bind_outputs(op, outs, env):
    for slot, vs in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for var, val in zip(vs, vals):
            if val is not None:
                env[var.name] = val


def infer_op_shapes(op):
    """Build-time shape/dtype inference by abstract-evaluating the rule.

    The dynamic batch dim (-1) is stood in by DYN_DIM and mapped back; this
    replaces the reference's per-op C++ InferShape functions.
    """
    rule = get_rule(op.type)

    def spec_of(var):
        if var.shape is None:
            return None
        s = var._spec()
        if var.lod_level and var.lod_level > 0:
            # padded layout [batch, time, ...]; shape already carries both
            # dynamic dims (see layers/io.py:data)
            batch = s.shape[0]
            lens = jax.ShapeDtypeStruct((batch,), np.int32)
            if var.lod_level > 1:
                return SeqValue(s, lens, jax.ShapeDtypeStruct((batch,), np.int32))
            return SeqValue(s, lens)
        return s

    ins = {slot: [spec_of(v) for v in vs] for slot, vs in op.inputs.items()}

    def f():
        key = jax.random.key(0)
        ctx = Ctx(key, op_index=0, is_test=bool(op.attrs.get('is_test', False)))
        concrete_ins = {
            slot: [jnp.zeros(s.data.shape, s.data.dtype) if isinstance(s, SeqValue)
                   else (jnp.zeros(s.shape, s.dtype) if s is not None else None)
                   for s in vs]
            for slot, vs in ins.items()}
        # re-wrap SeqValues
        for slot, vs in ins.items():
            for i, s in enumerate(vs):
                if isinstance(s, SeqValue):
                    concrete_ins[slot][i] = SeqValue(
                        concrete_ins[slot][i],
                        jnp.ones(s.lengths.shape, s.lengths.dtype))
        return rule(concrete_ins, op.attrs, ctx)

    try:
        outs = jax.eval_shape(f)
    except Exception:
        return  # shape inference is best-effort at build time

    for slot, vs in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for var, val in zip(vs, vals):
            if val is None:
                continue
            spec = val.data if isinstance(val, SeqValue) else val
            # DYN_DIM is prime, so any multiple of it can only have come
            # from the dynamic batch dim (tiled/merged by expand/reshape)
            shape = tuple(-1 if d % DYN_DIM == 0 and d > 0 else int(d)
                          for d in spec.shape)
            var.shape = shape
            from . import core
            var.dtype = core.convert_dtype(spec.dtype)
            if isinstance(val, SeqValue) and var.lod_level == 0:
                var.lod_level = 1
