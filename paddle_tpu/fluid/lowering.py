"""Op lowering registry: Fluid op symbols -> JAX.

TPU-first replacement for the reference's per-op C++/CUDA kernel registry
(paddle/fluid/framework/op_registry.h + operators/*_op.cu). Instead of a
kernel per (op, Place, dtype), each op type has ONE pure-JAX rule. The
Executor symbolically evaluates a whole Program through these rules inside a
single jax.jit trace, so XLA sees the entire training step as one module and
fuses across op boundaries (the reference pays a kernel launch per op).

The same rules power build-time shape inference via jax.eval_shape
(framework.Block.append_op), so op semantics are defined exactly once.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp

from .framework import DYN_DIM

_RULES = {}
_BLOCK_RULES = {}


class NoRuleError(KeyError):
    pass


class InferShapeError(ValueError):
    """A lowering rule failed to abstract-eval at program-build time under
    strict inference (framework.strict_infer_shape / PADDLE_TPU_STRICT_INFER)
    — the message names the op type and the user callsite that built it."""


def register(op_type):
    def deco(fn):
        _RULES[op_type] = fn
        return fn
    return deco


def register_block_op(op_type):
    """Register a structured-control-flow rule.

    Unlike plain rules (ins, attrs, ctx) -> outs, a block rule receives
    (op, env, ctx) and mutates env: it must execute its sub-block(s) itself
    (via run_block) under lax.while_loop / lax.scan / predicated select.
    This replaces the reference's C++ WhileOp/ConditionalBlockOp sub-scope
    interpreters (paddle/fluid/operators/while_op.cc,
    conditional_block_op.cc) with XLA-native structured control flow.
    """
    def deco(fn):
        _BLOCK_RULES[op_type] = fn
        return fn
    return deco


def get_rule(op_type):
    try:
        return _RULES[op_type]
    except KeyError:
        raise NoRuleError("no lowering rule for op %r" % op_type)


def has_rule(op_type):
    return op_type in _RULES


class Ctx(object):
    """Per-op lowering context: PRNG key, run mode, target platform
    (the Executor's Place decides this — jax.default_backend() lies when a
    TPU plugin is present but the computation is placed on CPU), and the
    device mesh the step is compiled against (None = single device) so
    mesh-aware rules (moe_mlp) can shard_map over it. `manual_axes` names
    mesh axes the op is ALREADY manual over (inside a shard_map body, e.g.
    the pipeline region): rules that would otherwise open their own
    shard_map (sp attention) must instead use the per-shard collective
    bodies on those axes."""

    __slots__ = ('key', 'op_index', 'is_test', 'amp', 'platform', 'mesh',
                 'manual_axes')

    def __init__(self, key, op_index=0, is_test=False, amp=False,
                 platform='cpu', mesh=None, manual_axes=frozenset()):
        self.key = key
        self.op_index = op_index
        self.is_test = is_test
        self.amp = amp
        self.platform = platform
        self.mesh = mesh
        self.manual_axes = manual_axes

    def rng(self):
        return jax.random.fold_in(self.key, self.op_index)


def amp_cast(ctx, *xs):
    """Under AMP, cast fp32 matmul/conv operands to bf16 for the MXU."""
    if not ctx.amp:
        return xs if len(xs) > 1 else xs[0]
    out = tuple(x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x
                for x in xs)
    return out if len(out) > 1 else out[0]


def use_kernel(ctx, name):
    """Trace-time pallas-kernel routing for lowering rules
    (docs/perf.md#kernel-layer): True iff kernel `name` is enabled via
    the ops.kernels knob (env PADDLE_TPU_KERNELS / kernels.configure).
    Records the decision on the kernels.dispatch/fallback counters, so
    every rule answers "which variant did this compile carry" in the
    obs report. Enablement is process-level, not a Ctx field — the
    Executor keys its compile cache on kernels.signature() so a knob
    flip can never be served a stale cached step. Rules keep their
    original jnp code as the False branch: that IS the fallback
    contract (knob off == byte-identical to the pre-kernel lowering).
    """
    from ..ops import kernels
    use = kernels.enabled(name)
    kernels.note_dispatch(name, use)
    return use


class SeqValue(object):
    """Runtime value of a lod_level>0 Variable: dense padded data + lengths.

    TPU-first replacement for LoDTensor's flattened [total_tokens, d] layout
    (reference paddle/fluid/framework/lod_tensor.h): static shapes
    [batch, max_len, ...] keep XLA happy; `lengths` int32[batch] carries the
    ragged structure of the INNERMOST LoD level; masked ops consult it.
    Nested LoD of arbitrary depth (the reference's recursive LoD table)
    keeps every level above the innermost in `outer_lengths`, a tuple of
    int32 vectors ordered outermost-first: level k's entries are lengths
    measured in units of level k+1's sequences, and the innermost level
    (`lengths`) is measured in tokens/rows. A bare array is accepted for
    the common 2-level case and normalised to a 1-tuple.

    `beam_cap` marks the CAPACITY form of the LoD beam-search decoder
    (ops_impl/lod_beam.py): data [B*K, ...] with each source's live rows
    compacted to the front of its K-row block. The flag is static pytree
    aux — it survives jit/while_loop round trips — and is set ONLY by
    normalize_capacity, the While capacity-widening pass, and the beam
    ops themselves, so ordinary 2-level LoD data whose shapes happen to
    look capacity-like (uniform group counts) can never be misrouted onto
    the beam path (round-5 ADVICE, lod_beam.is_beam_form).
    """

    __slots__ = ('data', 'lengths', 'outer_lengths', 'beam_cap')

    def __init__(self, data, lengths, outer_lengths=None, beam_cap=False):
        self.data = data
        self.lengths = lengths
        if outer_lengths is not None and not isinstance(outer_lengths, tuple):
            if isinstance(outer_lengths, list):
                outer_lengths = tuple(outer_lengths)
            else:
                outer_lengths = (outer_lengths,)
        self.outer_lengths = outer_lengths or None
        self.beam_cap = bool(beam_cap)

    @property
    def max_len(self):
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32):
        """[batch, max_len] validity mask."""
        t = self.data.shape[1]
        return (jnp.arange(t)[None, :] < self.lengths[:, None]).astype(dtype)

    def tree_flatten(self):
        n_outer = len(self.outer_lengths) if self.outer_lengths else 0
        return (self.data, self.lengths) + (self.outer_lengths or ()), \
            (n_outer, self.beam_cap)

    @classmethod
    def tree_unflatten(cls, aux, children):
        n_outer, beam_cap = aux if isinstance(aux, tuple) else (aux, False)
        outer = tuple(children[2:2 + n_outer]) if n_outer else None
        return cls(children[0], children[1], outer, beam_cap=beam_cap)


jax.tree_util.register_pytree_node(
    SeqValue,
    lambda s: s.tree_flatten(),
    lambda aux, ch: SeqValue.tree_unflatten(aux, ch))


class SparseRows(object):
    """Sparse gradient of an embedding table: the rows actually touched.

    TPU-native analogue of the reference's SelectedRows
    (paddle/fluid/framework/selected_rows.h; lookup_table_op.cc emits one
    as the table grad when is_sparse=True). `ids` int32[N] are the looked-up
    row indices (duplicates allowed, in lookup order), `rows` [N, D] the
    corresponding per-occurrence gradients; the equivalent dense gradient
    is scatter-add(zeros(dense_shape), ids, rows). Optimizer rules
    (ops_impl/optim_ops.py) consume it with index-based row updates, so the
    vocab-sized dense @GRAD buffer never materializes in HBM. Static shapes
    throughout (N = batch positions, not unique count) keep XLA happy.

    Sharded case (docs/embedding.md): `dense_shape` is always the GLOBAL
    table shape — under a mesh with a row-sharded table the [N, D] rows
    stay batch-sized (merged replicated by _merge_sparse) while the
    optimizer's row scatter partitions per shard, so neither layout ever
    builds the dense buffer."""

    __slots__ = ('ids', 'rows', 'dense_shape')

    def __init__(self, ids, rows, dense_shape):
        self.ids = ids
        self.rows = rows
        self.dense_shape = tuple(dense_shape)

    @property
    def dtype(self):
        return self.rows.dtype

    def astype(self, dtype):
        return SparseRows(self.ids, self.rows.astype(dtype),
                          self.dense_shape)

    def to_dense(self):
        out = jnp.zeros(self.dense_shape, self.rows.dtype)
        return out.at[self.ids].add(self.rows)


jax.tree_util.register_pytree_node(
    SparseRows,
    lambda s: ((s.ids, s.rows), s.dense_shape),
    lambda shape, ch: SparseRows(ch[0], ch[1], shape))


def data_of(v):
    return v.data if isinstance(v, SeqValue) else v


def like(template, new_data):
    """Wrap new_data with template's sequence structure (if any)."""
    if isinstance(template, SeqValue):
        return SeqValue(new_data, template.lengths, template.outer_lengths,
                        beam_cap=template.beam_cap)
    return new_data


def first_seq(*vals):
    for v in vals:
        if isinstance(v, SeqValue):
            return v
    return None


def run_op(op, env, ctx):
    """Resolve an op's inputs from env, apply its rule, bind outputs.

    Each rule traces under jax.named_scope('<op.type>_<op_index>'), so the
    XLA module's per-instruction metadata op_name carries the Fluid op it
    came from: profiler traces and HLO dumps of the COMPILED fused step map
    back to program ops (the reference's per-op C++ event tracer,
    profiler.py:81-130, attributes the real run the same way — here the
    attribution survives fusion instead of requiring the eager path)."""
    with jax.named_scope('%s_%d' % (op.type, ctx.op_index)):
        if op.type in _BLOCK_RULES:
            _BLOCK_RULES[op.type](op, env, ctx)
            return
        rule = get_rule(op.type)
        ins = {slot: [env[v.name] for v in vs]
               for slot, vs in op.inputs.items()}
        outs = rule(ins, op.attrs, ctx)
    _bind_outputs(op, outs, env)


def run_block(block, env, ctx):
    """Execute every op of a (sub-)block against env, in place.

    The PRNG stream stays distinct per (block, op) position so dropout etc.
    inside loop bodies doesn't collide with the outer ops' streams.
    """
    base = block.idx * 4096
    for i, op in enumerate(block.ops):
        run_op(op, env, Ctx(ctx.key, base + i, is_test=ctx.is_test,
                            amp=ctx.amp, platform=ctx.platform,
                            mesh=ctx.mesh, manual_axes=ctx.manual_axes))


# Default slot count for LoDTensorArray buffers (see ArrayValue). Layers
# read layers/control_flow.py:ARRAY_CAPACITY (initialized from this) at
# call time; this is the single fallback for ops lacking a capacity attr.
DEFAULT_ARRAY_CAPACITY = 128


class ArrayValue(object):
    """Runtime value of a LOD_TENSOR_ARRAY variable.

    The reference's LoDTensorArray is a C++ vector<LoDTensor> grown by
    array_write ops inside While loops (operators/array_write_op.cc). Under
    XLA everything must be statically shaped, so an array is a preallocated
    ring of `capacity` slots [capacity, *elem] plus a live-length scalar;
    writes are lax.dynamic_update_slice, reads dynamic_index_in_dim. This
    makes arrays legal lax.while_loop carries.

    Elements may be LoD-carrying SeqValues (the book's beam-search decoder
    stores 2-level selected_ids/scores in arrays): `buffer` is then a TUPLE
    of stacked leaf buffers (data, lengths, *outer_lengths) and `n_outer`
    (static) says how many trailing buffers are outer LoD levels; -1 marks
    a plain dense element. `beam` (static aux, like SeqValue.beam_cap)
    records that the stored elements are capacity-form beam values, so
    array_read rebuilds them with the flag intact."""

    __slots__ = ('buffer', 'length', 'n_outer', 'beam')

    def __init__(self, buffer, length, n_outer=-1, beam=False):
        self.buffer = buffer
        self.length = length
        self.n_outer = n_outer
        self.beam = bool(beam)

    @property
    def is_seq(self):
        return self.n_outer >= 0

    def read(self, i):
        """Element at slot i (rebuilds the SeqValue for seq-backed arrays)."""
        take = lambda b: jax.lax.dynamic_index_in_dim(b, i, axis=0,
                                                      keepdims=False)
        if not self.is_seq:
            return take(self.buffer)
        leaves = tuple(take(b) for b in self.buffer)
        outer = leaves[2:2 + self.n_outer] if self.n_outer else None
        return SeqValue(leaves[0], leaves[1], outer, beam_cap=self.beam)

    @staticmethod
    def _grow_rows(buf, rows_new, n_sources=None):
        """[cap, r_old, ...] -> [cap, rows_new, ...]: row i moves to
        i * stride (the LoD beam capacity convention — each source's rows
        must land at the START of its capacity block; see
        ops_impl/lod_beam.py). That placement is only correct when every
        source owns exactly ONE narrow row (r_old == number of sources);
        a multi-row-per-source init would be scattered at stride intervals
        INSIDE each block, silently breaking the rows-compacted-to-front
        invariant that rows_live/the live-mask assume — so when the caller
        knows the source count, widening anything else raises loudly
        (round-5 ADVICE)."""
        r_old = buf.shape[1]
        if rows_new == r_old:
            return buf
        if rows_new % r_old:
            raise ValueError(
                'array_write: element rows grew %d -> %d; capacity '
                'widening needs an integer stride' % (r_old, rows_new))
        if n_sources is not None and r_old != n_sources:
            raise ValueError(
                'array_write: cannot widen %d rows to capacity %d for %d '
                'sources — stride placement is only valid from one row '
                'per source (%d rows); compact the init to one row per '
                'source before the loop' % (r_old, rows_new, n_sources,
                                            n_sources))
        out = jnp.zeros((buf.shape[0], rows_new) + buf.shape[2:],
                        buf.dtype)
        return out.at[:, ::rows_new // r_old].set(buf)

    def _grown_to(self, x):
        """Widen/convert the buffers so a write of `x` fits (the book's
        decode idiom writes one row per source before the While, beam_size
        rows per source inside it). Widening follows the beam capacity
        convention, so the result is beam-flagged; the source count from
        x's outer LoD gates _grow_rows' one-row-per-source check."""
        if isinstance(x, SeqValue):
            n_outer = len(x.outer_lengths or ())
            n_src = (x.outer_lengths[0].shape[0]
                     if x.outer_lengths else None)
            if not self.is_seq:
                data = self._grow_rows(self.buffer, x.data.shape[0],
                                       n_sources=n_src)
                stride = x.data.shape[0] // self.buffer.shape[1]
                lens = jnp.zeros((data.shape[0], x.data.shape[0]),
                                 jnp.int32)
                lens = lens.at[:, ::stride].set(1)
                outer = tuple(
                    jnp.ones((data.shape[0],) + o.shape, o.dtype)
                    for o in (x.outer_lengths or ()))
                return ArrayValue((data, lens) + outer, self.length,
                                  n_outer, beam=True)
            d0 = self.buffer[0]
            if d0.ndim == x.data.ndim + 2 and d0.shape[2] == 1:
                # padded 2-level feed slots [B, max_len=1, ...] -> flat rows
                d0 = d0.reshape(d0.shape[:2] + d0.shape[3:])
            data = self._grow_rows(d0, x.data.shape[0], n_sources=n_src)
            lens = self._grow_rows(self.buffer[1], x.lengths.shape[0],
                                   n_sources=n_src)
            return ArrayValue((data, lens) + self.buffer[2:], self.length,
                              self.n_outer,
                              beam=self.beam or data is not d0)
        if not self.is_seq:
            return ArrayValue(self._grow_rows(self.buffer,
                                              data_of(x).shape[0]),
                              self.length, -1, beam=self.beam)
        return self

    def _elem_fits(self, x):
        if isinstance(x, SeqValue):
            return (self.is_seq
                    and self.n_outer == len(x.outer_lengths or ())
                    and self.buffer[0].shape[1:] == x.data.shape
                    and self.buffer[1].shape[1:] == x.lengths.shape)
        return (not self.is_seq
                and self.buffer.shape[1:] == data_of(x).shape)

    def write(self, i, x):
        """New ArrayValue with slot i <- x; the buffers grow (capacity
        convention) when x is wider than the current slots."""
        if not isinstance(x, SeqValue) and self.is_seq:
            # dense write into an LoD array (e.g. an encoder state fed to
            # the decode idiom's state array): adopt one full-length group
            # per row
            x = SeqValue(data_of(x),
                         jnp.ones((data_of(x).shape[0],), jnp.int32),
                         tuple(jnp.ones(b.shape[1:], b.dtype)
                               for b in self.buffer[2:2 + self.n_outer])
                         or None, beam_cap=self.beam)
        if isinstance(x, SeqValue) and not self._elem_fits(x):
            slot = self.buffer[0] if self.is_seq else self.buffer
            if (x.data.ndim == slot.ndim and x.data.shape[1] == 1
                    and slot.shape[1:] != x.data.shape):
                # [rows, max_len=1, ...] padded element vs flat-row slots
                # (the decode idiom's pre-loop feeds): drop the singleton
                # time dim before fitting/growing
                x = SeqValue(x.data[:, 0], x.lengths, x.outer_lengths,
                             beam_cap=x.beam_cap)
        if not self._elem_fits(x):
            grown = self._grown_to(x)
            if not grown._elem_fits(x):
                def shp(v):
                    if isinstance(v, SeqValue):
                        return ('seq', v.data.shape, v.lengths.shape,
                                tuple(o.shape
                                      for o in (v.outer_lengths or ())))
                    return getattr(v, 'shape', v)
                raise TypeError(
                    'array_write: element %r does not fit (and cannot '
                    'grow to fit) array slots %r'
                    % (shp(x), [b.shape for b in grown.buffer]
                       if grown.is_seq else grown.buffer.shape))
            return grown.write(i, x)
        put = lambda b, v: jax.lax.dynamic_update_index_in_dim(
            b, v.astype(b.dtype), i, axis=0)
        if isinstance(x, SeqValue):
            leaves = (x.data, x.lengths) + tuple(x.outer_lengths or ())
            assert len(leaves) == len(self.buffer)  # _elem_fits checked
            buf = tuple(put(b, v) for b, v in zip(self.buffer, leaves))
        else:
            buf = put(self.buffer, x)
        cap = (self.buffer[0] if self.is_seq else self.buffer).shape[0]
        length = jnp.minimum(jnp.maximum(self.length, i + 1), cap)
        return ArrayValue(buf, length, self.n_outer,
                          beam=self.beam or getattr(x, 'beam_cap', False))

    @classmethod
    def fresh(cls, x, capacity):
        """Empty array sized for elements shaped like x."""
        z = lambda v: jnp.zeros((capacity,) + tuple(v.shape), v.dtype)
        if isinstance(x, SeqValue):
            leaves = (x.data, x.lengths) + tuple(x.outer_lengths or ())
            return cls(tuple(z(v) for v in leaves),
                       jnp.asarray(0, jnp.int32),
                       len(x.outer_lengths or ()),
                       beam=x.beam_cap)
        return cls(z(x), jnp.asarray(0, jnp.int32), -1)


jax.tree_util.register_pytree_node(
    ArrayValue,
    lambda a: ((a.buffer, a.length), (a.n_outer, a.beam)),
    lambda aux, ch: ArrayValue(ch[0], ch[1], aux[0], beam=aux[1])
    if isinstance(aux, tuple) else ArrayValue(ch[0], ch[1], aux))


def _bind_outputs(op, outs, env):
    for slot, vs in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for var, val in zip(vs, vals):
            if val is not None:
                env[var.name] = val


def spec_of(var):
    """Build-time abstract value of a Variable: a jax.ShapeDtypeStruct (the
    dynamic batch dim stood in by DYN_DIM), a SeqValue of specs for
    lod_level>0 vars, or None when the shape is undeclared. Shared by
    append_op's inference and the fluid.analysis shape pass."""
    if var.shape is None:
        return None
    s = var._spec()
    if var.lod_level and var.lod_level > 0:
        # padded layout [batch, time, ...]; shape already carries both
        # dynamic dims (see layers/io.py:data)
        batch = s.shape[0]
        lens = jax.ShapeDtypeStruct((batch,), np.int32)
        if var.lod_level > 1:
            return SeqValue(s, lens, jax.ShapeDtypeStruct((batch,), np.int32))
        return SeqValue(s, lens)
    return s


def abstract_eval(op, in_specs):
    """Abstract-evaluate op's lowering rule over per-slot input specs
    ({slot: [spec | SeqValue-of-specs | None, ...]}) via jax.eval_shape.
    Returns the rule's output structure with ShapeDtypeStructs for arrays.
    Raises NoRuleError for unregistered ops and whatever the rule raises
    when the specs are inconsistent (the caller decides strictness)."""
    rule = get_rule(op.type)

    def f():
        key = jax.random.key(0)
        ctx = Ctx(key, op_index=0, is_test=bool(op.attrs.get('is_test', False)))
        concrete_ins = {
            slot: [jnp.zeros(s.data.shape, s.data.dtype) if isinstance(s, SeqValue)
                   else (jnp.zeros(s.shape, s.dtype) if s is not None else None)
                   for s in vs]
            for slot, vs in in_specs.items()}
        # re-wrap SeqValues
        for slot, vs in in_specs.items():
            for i, s in enumerate(vs):
                if isinstance(s, SeqValue):
                    concrete_ins[slot][i] = SeqValue(
                        concrete_ins[slot][i],
                        jnp.ones(s.lengths.shape, s.lengths.dtype))
        return rule(concrete_ins, op.attrs, ctx)

    return jax.eval_shape(f)


def shape_from_spec(spec):
    """Declared-shape view of an inferred ShapeDtypeStruct: DYN_DIM is
    prime, so any multiple of it can only have come from the dynamic batch
    dim (tiled/merged by expand/reshape) and maps back to -1."""
    return tuple(-1 if d % DYN_DIM == 0 and d > 0 else int(d)
                 for d in spec.shape)


def infer_op_shapes(op, strict=False):
    """Build-time shape/dtype inference by abstract-evaluating the rule.

    The dynamic batch dim (-1) is stood in by DYN_DIM and mapped back; this
    replaces the reference's per-op C++ InferShape functions. Best-effort
    by default (a failing rule leaves declared shapes alone); with
    strict=True a failure raises InferShapeError naming the op type and
    the callsite that built it (framework.strict_infer_shape)."""
    ins = {slot: [spec_of(v) for v in vs] for slot, vs in op.inputs.items()}

    try:
        outs = abstract_eval(op, ins)
    except NoRuleError:
        raise
    except Exception as e:
        if strict:
            site = getattr(op, 'callsite', None)
            raise InferShapeError(
                "shape inference failed for op %r%s: %s: %s (inputs: %s)"
                % (op.type,
                   ' built at %s' % site if site else '',
                   type(e).__name__, e,
                   {k: [getattr(s, 'shape', None) if not isinstance(s, SeqValue)
                        else ('seq', s.data.shape) for s in vs]
                    for k, vs in ins.items()}))
        return  # shape inference is best-effort at build time

    for slot, vs in op.outputs.items():
        vals = outs.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for var, val in zip(vs, vals):
            if val is None:
                continue
            spec = val.data if isinstance(val, SeqValue) else val
            var.shape = shape_from_spec(spec)
            from . import core
            var.dtype = core.convert_dtype(spec.dtype)
            if isinstance(val, SeqValue) and var.lod_level == 0:
                var.lod_level = 1
