"""append_backward: gradients for a loss.

Parity: reference python/paddle/fluid/backward.py:469 — there, a grad op is
appended per forward op (each with a hand-written C++ GradKernel).

TPU-first redesign: one `autodiff` op is planted instead; at lowering the
Executor differentiates the already-traced forward with jax.grad, so every
op's backward comes from JAX AD of the same rule that defines its forward —
no per-op grad kernels, and XLA fuses forward+backward into one module.
The public contract is unchanged: `<param>@GRAD` Variables appear in the
block and (param, grad) pairs are returned for the optimizer.
"""
from . import framework
from .framework import Parameter, grad_var_name

__all__ = ['append_backward']


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    assert loss is not None, "loss is required by append_backward"
    program = loss.block.program
    block = program.global_block()

    if parameter_list is not None:
        names = set(parameter_list)
        params = [p for p in block.all_parameters() if p.name in names]
    else:
        params = [p for p in block.all_parameters()]
    no_grad = set(no_grad_set or [])
    params = [p for p in params
              if p.trainable and not p.stop_gradient and p.name not in no_grad]

    forward_op_count = len(block.ops)
    grads = []
    for p in params:
        g = block.create_var(name=grad_var_name(p.name), shape=p.shape,
                             dtype=p.dtype, persistable=False)
        grads.append(g)

    block.append_op(
        type='autodiff',
        inputs={'Loss': [loss]},
        outputs={'Grads': grads},
        attrs={
            'loss_name': loss.name,
            'param_names': [p.name for p in params],
            'grad_names': [g.name for g in grads],
            'forward_op_count': forward_op_count,
            'op_role': framework.ROLE_BACKWARD,
        },
        infer_shape=False)

    return [(p, g) for p, g in zip(params, grads)]
