"""ParamAttr / WeightNormParamAttr.

Parity: reference python/paddle/fluid/param_attr.py.
"""
from .initializer import Initializer, Xavier, Constant
from .regularizer import WeightDecayRegularizer

__all__ = ['ParamAttr', 'WeightNormParamAttr']


class ParamAttr(object):
    def __init__(self,
                 name=None,
                 initializer=None,
                 learning_rate=1.0,
                 regularizer=None,
                 trainable=True,
                 gradient_clip=None,
                 do_model_average=None,
                 sharding=None):
        # do_model_average default None (= averaged): the reference's
        # ParamAttr declares False but its _to_kwargs/Parameter key
        # mismatch makes every default param land as None, and
        # ModelAverage includes params with do_model_average != False —
        # we reproduce that observable behavior directly.
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average
        # GSPMD sharding annotation (docs/parallel.md): per-dim mesh-axis
        # names, e.g. sharding=('model', None) partitions dim 0 over the
        # 'model' axis of the Program's set_mesh() spec. Normalized here
        # so a bad spec fails at the layer call that wrote it.
        from .framework import normalize_sharding
        self.sharding = normalize_sharding(sharding)

    def set_default_initializer(self, initializer):
        if initializer is None:
            if self.initializer is None:
                raise ValueError("ParamAttr.initializer is not set")
            return
        if self.initializer is not None:
            return
        self.initializer = initializer

    def set_default_param_initializer(self):
        self.set_default_initializer(Xavier())

    def set_default_bias_initializer(self):
        self.set_default_initializer(Constant(0.0))

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        elif isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        elif isinstance(arg, ParamAttr):
            return arg
        elif isinstance(arg, str):
            return ParamAttr(name=arg)
        elif isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        elif isinstance(arg, WeightDecayRegularizer):
            return ParamAttr(regularizer=arg)
        elif isinstance(arg, bool):
            # False suppresses the parameter entirely (reference
            # param_attr.py:_to_attr returns False -> append_bias_op skips)
            return ParamAttr.to_attr(None) if arg else False
        else:
            raise TypeError("cannot convert %r to ParamAttr" % (arg,))

    def to_kwargs(self, with_initializer=False):
        kwargs = {
            'name': self.name,
            'optimize_attr': {'learning_rate': self.learning_rate},
            'regularizer': self.regularizer,
            'trainable': self.trainable,
            'gradient_clip_attr': self.gradient_clip,
            'do_model_average': self.do_model_average,
            'sharding': self.sharding,
        }
        if with_initializer:
            kwargs['initializer'] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    """Weight-norm reparameterization attr (reference param_attr.py)."""

    def __init__(self, dim=None, **kwargs):
        super(WeightNormParamAttr, self).__init__(**kwargs)
        self.dim = dim
