"""Go-style CSP primitives: channels, send/recv, select, goroutines.

Parity: reference python/paddle/fluid/concurrency.py (make_channel,
channel_send, channel_recv, channel_close, Select; Go in control_flow's
spirit). The reference lowers these to C++ channel ops executed by
concurrent scope threads inside the Fluid program — a model that does not
map onto a single compiled XLA module, and which the reference itself
retired shortly after v0.14.

TPU-first redesign: channels here are HOST-side pipeline primitives
(thread-safe rendezvous/buffered queues) for composing data producers,
prefetchers and trainers around the compiled step — the role the channel
ops actually played in reference programs (feeding readers), kept OUT of
the jitted graph where XLA's async copy/infeed machinery already owns
concurrency. `Go` runs a Python callable on a daemon thread; `Select`
blocks on the first ready case, Go-style.
"""
import queue
import threading

__all__ = [
    'Go', 'make_channel', 'channel_send', 'channel_recv', 'channel_close',
    'Select'
]

_CLOSED = object()


class Channel(object):
    """Typed FIFO channel. capacity=0 gives Go's unbuffered rendezvous
    (send blocks until a receiver takes the value)."""

    def __init__(self, dtype=None, capacity=0):
        self.dtype = dtype
        self.capacity = capacity
        # rendezvous: a 1-slot queue + handshake event per send
        self._q = queue.Queue(maxsize=capacity if capacity > 0 else 1)
        self._unbuffered = capacity == 0
        self._closed = threading.Event()
        self._taken = threading.Condition()
        self._pending = 0

    def send(self, value):
        if self._closed.is_set():
            return False
        with self._taken:
            self._pending += 1
        self._q.put(value)
        if self._unbuffered:
            with self._taken:
                while self._pending > 0 and not self._closed.is_set():
                    self._taken.wait(timeout=0.05)
        return not self._closed.is_set()

    def recv(self):
        while True:
            try:
                v = self._q.get(timeout=0.05)
                with self._taken:
                    self._pending -= 1
                    self._taken.notify_all()
                if v is _CLOSED:
                    self._q.put(_CLOSED)  # keep draining receivers unblocked
                    return None, False
                return v, True
            except queue.Empty:
                if self._closed.is_set():
                    return None, False

    def poll(self):
        """Non-blocking readiness check for Select."""
        return not self._q.empty() or self._closed.is_set()

    def close(self):
        self._closed.set()
        try:
            self._q.put_nowait(_CLOSED)
        except queue.Full:
            pass
        with self._taken:
            self._taken.notify_all()


def make_channel(dtype, capacity=0):
    return Channel(dtype=dtype, capacity=capacity)


def channel_send(channel, value, is_copy=False):
    if is_copy:
        import copy as _copy
        value = _copy.deepcopy(value)
    return channel.send(value)


def channel_recv(channel, return_value=None):
    value, ok = channel.recv()
    if not ok:
        return return_value, False
    return value, True


def channel_close(channel):
    channel.close()


class Go(object):
    """Run `target(*args)` concurrently (reference Go block -> goroutine).

    Usage::

        with Go() as g:
            g.run(producer, ch)
    or  Go(target=producer, args=(ch,)).start()
    """

    def __init__(self, target=None, args=(), name=None):
        self._threads = []
        if target is not None:
            self.run(target, *args)

    def run(self, target, *args, **kwargs):
        t = threading.Thread(target=target, args=args, kwargs=kwargs)
        t.daemon = True
        t.start()
        self._threads.append(t)
        return t

    def start(self):
        return self

    def join(self, timeout=None):
        for t in self._threads:
            t.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class Select(object):
    """Block until one case is ready, then run its body (reference Select).

    Cases are (channel, 'recv'|'send', value_or_callback)::

        sel = Select()
        sel.case(ch_a, 'recv', on_a)          # on_a(value)
        sel.case(ch_b, 'send', 42, on_sent)   # optional post-send callback
        sel.default(on_idle)                  # optional, makes it non-blocking
        idx = sel()                           # index of the fired case
    """

    def __init__(self, name=None):
        self._cases = []
        self._default = None

    def case(self, channel, action, *payload):
        if action not in ('recv', 'send'):
            raise ValueError("Select case action must be 'recv' or 'send'")
        self._cases.append((channel, action, payload))
        return self

    def default(self, callback=None):
        self._default = callback or (lambda: None)
        return self

    def __call__(self, timeout=None):
        import time
        deadline = None if timeout is None else time.time() + timeout
        while True:
            for i, (ch, action, payload) in enumerate(self._cases):
                if action == 'recv':
                    if ch.poll():
                        v, ok = ch.recv()
                        if payload and callable(payload[0]):
                            payload[0](v) if ok else None
                        return i
                else:  # send
                    if not ch._q.full() and not ch._closed.is_set():
                        ch.send(payload[0])
                        if len(payload) > 1 and callable(payload[1]):
                            payload[1]()
                        return i
            if self._default is not None:
                self._default()
                return -1
            if deadline is not None and time.time() > deadline:
                raise TimeoutError('Select timed out')
            time.sleep(0.001)
