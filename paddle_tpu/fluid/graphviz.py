"""Minimal dot-language builder used by net_drawer/debugger.

Parity: reference python/paddle/fluid/graphviz.py (Graph/Node/Edge + the
GraphPreviewGenerator convenience layer). Pure string emission — rendering
to an image shells out to `dot` only if present.
"""
import os
import subprocess

__all__ = ['Graph', 'Node', 'Edge', 'GraphPreviewGenerator']


def _attr_str(attrs):
    if not attrs:
        return ''
    return '[' + ', '.join('%s="%s"' % (k, v)
                           for k, v in sorted(attrs.items())) + ']'


class Node(object):
    counter = 0

    def __init__(self, label, prefix='node', **attrs):
        Node.counter += 1
        self.name = '%s_%d' % (prefix, Node.counter)
        self.label = label
        self.attrs = attrs

    def __str__(self):
        attrs = dict(self.attrs)
        attrs['label'] = self.label
        return '%s %s;' % (self.name, _attr_str(attrs))


class Edge(object):
    def __init__(self, source, target, **attrs):
        self.source = source
        self.target = target
        self.attrs = attrs

    def __str__(self):
        return '%s -> %s %s;' % (self.source.name, self.target.name,
                                 _attr_str(self.attrs))


class Graph(object):
    def __init__(self, title, **attrs):
        self.title = title
        self.attrs = attrs
        self.nodes = []
        self.edges = []

    def add_node(self, label, prefix='node', **attrs):
        node = Node(label, prefix=prefix, **attrs)
        self.nodes.append(node)
        return node

    def add_edge(self, source, target, **attrs):
        edge = Edge(source, target, **attrs)
        self.edges.append(edge)
        return edge

    def compile(self, dot_path):
        """Write the .dot file; returns the path of the image `dot` would
        produce next to it."""
        with open(dot_path, 'w') as f:
            f.write(str(self))
        return dot_path[:-4] + '.png' if dot_path.endswith('.dot') \
            else dot_path + '.png'

    def show(self, dot_path):
        """compile + best-effort render with graphviz `dot` if installed."""
        image = self.compile(dot_path)
        try:
            subprocess.run(['dot', '-Tpng', dot_path, '-o', image],
                           check=True, stdout=subprocess.DEVNULL,
                           stderr=subprocess.DEVNULL, timeout=60)
        except Exception:
            return None  # dot binary absent: the .dot file still exists
        return image

    def __str__(self):
        out = ['digraph G {']
        if self.title:
            out.append('  label="%s";' % self.title)
        out.extend('  %s="%s";' % (k, v) for k, v in sorted(self.attrs.items()))
        out.extend('  ' + str(n) for n in self.nodes)
        out.extend('  ' + str(e) for e in self.edges)
        out.append('}')
        return '\n'.join(out)


class GraphPreviewGenerator(object):
    """Convenience layer: parameters as ellipses, ops as rects, tmp vars
    dotted (reference graphviz.py:GraphPreviewGenerator)."""

    def __init__(self, title):
        self.graph = Graph(title, rankdir='TB')

    def add_param(self, name, data_type, highlight=False):
        label = '%s\\n%s' % (name, data_type)
        return self.graph.add_node(
            label, prefix='param', shape='ellipse', style='filled',
            fillcolor='lightcoral' if highlight else 'lightgrey')

    def add_op(self, opType, **kwargs):
        return self.graph.add_node(opType, prefix='op', shape='rect',
                                   style='rounded,filled',
                                   fillcolor='lightblue')

    def add_arg(self, name, highlight=False):
        return self.graph.add_node(
            name, prefix='arg', shape='box', style='dotted,filled',
            fillcolor='yellow' if highlight else 'white')

    def add_edge(self, source, target, **kwargs):
        return self.graph.add_edge(source, target, **kwargs)

    def __call__(self, path='temp.dot', show=False):
        if show:
            return self.graph.show(path)
        return self.graph.compile(path)
