"""High-level Trainer API.

Parity: reference python/paddle/fluid/trainer.py (Trainer:169,
CheckpointConfig:100, the Begin/End Epoch/Step events, build_feed_var_list:608)
— the train_func/optimizer_func loop used by every book chapter.

TPU-first notes: the reference's distribute-transpile-from-env branch
(pserver/NCCL2) is replaced by the mesh path — parallel=True runs the same
program GSPMD-sharded through ParallelExecutor (XLA inserts the ICI
collectives); multi-host setup goes through paddle_tpu.parallel.init_multihost.
Checkpoint/resume keeps the reference's crash-recovery semantics: periodic
persistable snapshots + (epoch, step) trainer args, auto-resumed when a
Trainer is constructed over a checkpoint dir, cleaned on successful finish.
"""
import contextlib
import os
import re

from .. import obs
from . import core
from . import framework
from . import io
from . import optimizer as opt_module
from . import parallel_executor
from . import unique_name
from .data_feeder import DataFeeder
from .executor import Executor, Scope, scope_guard

__all__ = [
    'Trainer', 'BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
    'EndStepEvent', 'CheckpointConfig',
]


class BeginEpochEvent(object):
    """reference trainer.py:40."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    """reference trainer.py:52."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    """reference trainer.py:64. Set self.fetch_metrics=False in the handler
    to skip fetching the train_func outputs this step."""

    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent(object):
    """reference trainer.py:83."""

    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig(object):
    """reference trainer.py:100."""

    def __init__(self, checkpoint_dir=None, max_num_checkpoints=3,
                 epoch_interval=1, step_interval=10, commit_timeout=60.0,
                 async_save=False, wallclock_interval_s=None):
        """commit_timeout: sharded-checkpoint commit wait (seconds) —
        how long process 0 waits for every peer's staged manifest before
        declaring the save uncommitted (docs/robustness.md#elastic).
        Irrelevant to the dense npz format.

        async_save: move the sharded-checkpoint file IO + commit protocol
        off the step path onto a background writer thread
        (utils.checkpoint.save_sharded_async). The step-boundary cost
        shrinks to the buffer snapshot (device->host shard copies, taken
        synchronously so the next step may donate the device buffers);
        the atomic staging + manifest-last + commit-rename protocol is
        unchanged, so a SIGKILL mid-async-save still never leaves a
        latest-looking torn serial. Emergency / preemption / host-loss
        flushes first drain the in-flight writer, then save
        SYNCHRONOUSLY — they commit (or stage loudly) before exit.
        Sharded-format only; the dense npz path ignores it.

        wallclock_interval_s: unbounded-stream cadence
        (Trainer.train_stream): ALSO checkpoint whenever this many
        seconds have passed since the last save, regardless of the step
        interval — an online trainer consuming a slow stream must bound
        recovery by wall clock, not step count. Epoch-based train()
        ignores it."""
        assert epoch_interval >= 1
        assert step_interval >= 1
        self.checkpoint_dir = (checkpoint_dir if checkpoint_dir is not None
                               else os.getcwd())
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = epoch_interval
        self.step_interval = step_interval
        self.commit_timeout = float(commit_timeout)
        self.async_save = bool(async_save)
        self.wallclock_interval_s = (float(wallclock_interval_s)
                                     if wallclock_interval_s is not None
                                     else None)
        self.epoch_id = 0
        self.step_id = 0
        self.load_serial = None


def check_and_get_place(place):
    """reference trainer.py:143 — default to the TPU when present."""
    if place is None:
        return (core.TPUPlace(0) if core.is_compiled_with_tpu()
                else core.CPUPlace())
    return place


def build_feed_var_list(program, feed_order=None):
    """reference trainer.py:608; feed_order None follows the program's
    data-var definition order."""
    if not isinstance(program, framework.Program):
        raise TypeError("The 'program' should be an object of Program")
    block = program.global_block()
    if feed_order is None:
        return [v for v in block.vars.values()
                if getattr(v, 'is_data', False)]
    if isinstance(feed_order, list):
        return [block.var(name) for name in feed_order]
    if not isinstance(feed_order, dict):
        raise TypeError("The 'feed_order' should be either None, list or dict.")
    if sorted(feed_order.values()) != list(range(len(feed_order))):
        raise ValueError("The values of 'feed_order' should be a permutation "
                         "of [0, len(feed_order))")
    return [block.var(name)
            for name, _ in sorted(feed_order.items(), key=lambda kv: kv[1])]


class Trainer(object):
    """reference trainer.py:169."""

    def __init__(self, train_func, optimizer_func, param_path=None,
                 place=None, parallel=False, checkpoint_config=None,
                 transpiler_fn=None, bundle_steps=1, sync='auto',
                 async_window=2, heartbeat=None, double_buffer=False):
        """transpiler_fn(train_program): optional hook applied after
        minimize — the high-level entry for the Program transpilers, e.g.
        lambda p: fluid.TensorParallelTranspiler(tp=2).transpile(p)
        (or SequenceParallel/Pipeline; TPU extension, the reference's
        Trainer had only the pserver path).

        Hot-loop pipelining (docs/perf.md):
          bundle_steps=K (K>1) runs K reader batches per device dispatch
          through Executor.run_bundle — one lax.scan-compiled module, one
          host round-trip per K steps. Begin/EndStepEvents still fire per
          logical step (End events carry that step's own metrics sliced
          from the bundle); BeginStepEvent.fetch_metrics is honored per
          BUNDLE (the first step's decision — a bundle is one compiled
          module with one fetch set). Periodic checkpoints are taken at
          bundle boundaries (the scope holds bundle-end state only).
          sync='async' (unbundled path) fetches metrics as lazy
          FetchHandles and keeps up to `async_window` steps in flight:
          the loss is only synced when the event handler reads it (or
          when the window evicts its oldest step), overlapping host
          bookkeeping with device execution.
          double_buffer=True moves the INPUT side off the critical path
          (docs/perf.md#overlap): a background prefetch thread
          (reader.pipeline.prefetch) runs the DataFeeder assembly — and,
          for plain single-device programs, the host->device transfer —
          of batch N+1 while step N executes, so the loop's per-step
          input wait (`trainer.input_stage` spans, the obs_report
          overlap ratio) reads ~0 in steady state. Values are
          bit-identical to the synchronous path: staging changes WHERE
          the feed work happens, never what is fed."""
        if bundle_steps < 1:
            raise ValueError('bundle_steps must be >= 1, got %r'
                             % (bundle_steps,))
        if sync not in ('auto', 'block', 'async'):
            raise ValueError("sync must be 'auto', 'block' or 'async', "
                             "got %r" % (sync,))
        if parallel and (bundle_steps > 1 or sync == 'async'):
            raise ValueError(
                'bundle_steps/sync="async" pipeline the single-program '
                'Executor hot loop; parallel=True (ParallelExecutor) '
                'does not compose with them — express dp via '
                'transpiler_fn instead')
        if bundle_steps > 1 and sync == 'async':
            raise ValueError(
                'bundle_steps=%d already amortizes the host round-trip '
                'over the bundle, and the bundled loop slices per-step '
                'metrics for its EndStepEvents (a blocking read); '
                "sync='async' applies to the unbundled loop — pick one"
                % bundle_steps)
        self.bundle_steps = int(bundle_steps)
        self.sync = sync
        self.async_window = max(1, int(async_window))
        self.double_buffer = bool(double_buffer)
        # input-overlap accounting (docs/perf.md#overlap): total seconds
        # the train loop actually WAITED for its next fed batch, and the
        # batches counted — bench.py's overlap phase reads these
        self.input_stage_s = 0.0
        self.batches_fed = 0
        # in-flight async sharded checkpoint (CheckpointConfig
        # async_save=True): at most ONE writer outstanding; every new
        # save, emergency flush, or cleanup drains it first
        self._async_ckpt = None
        self.__stop = False
        # preemption (SIGTERM/SIGINT while train() runs): the handler only
        # sets _preempt_requested; the loop finishes the in-flight step,
        # flushes an emergency checkpoint, and returns cleanly with
        # self.preempted = True. A fresh Trainer over the same checkpoint
        # dir resumes at the exact next step.
        self._preempt_requested = False
        self._preempt_signum = None
        self.preempted = False
        # elastic host-failure detection (docs/robustness.md#elastic):
        # a parallel.Heartbeat whose check() runs at every step boundary;
        # a stale peer flushes an emergency checkpoint and raises the
        # typed parallel.HostLost so a supervisor restarts on the
        # surviving topology. host_lost records what was detected.
        self.heartbeat = heartbeat
        self.host_lost = None
        # streaming-ids state (train_stream, docs/embedding.md): the
        # active {feed name: VocabTable} map serialized into every
        # checkpoint's meta, and the vocab meta recovered from a resumed
        # checkpoint (applied when train_stream() is handed its tables)
        self._stream_vocabs = None
        self._stream_resume_vocab = None
        self.parallel = parallel
        self.trainer_id = 0
        self.checkpoint_cfg = checkpoint_config
        if self.checkpoint_cfg:
            assert isinstance(self.checkpoint_cfg, CheckpointConfig)

        self.scope = Scope()
        self.startup_program = framework.Program()
        self.train_program = framework.Program()

        with self._prog_and_scope_guard():
            with unique_name.guard():
                outs = train_func()
                self.train_func_outputs = (outs if isinstance(outs, list)
                                           else [outs])
                self.test_program = self.train_program.clone(for_test=True)
                loss = self.train_func_outputs[0]
                optimizer = optimizer_func()
                if not isinstance(optimizer, opt_module.Optimizer):
                    raise TypeError(
                        "The optimizer should be an instance of Optimizer")
                optimizer.minimize(loss)
                if transpiler_fn is not None:
                    if self.parallel:
                        raise ValueError(
                            'parallel=True builds its own dp-only mesh and '
                            'would silently drop the transpiler_fn '
                            'annotations; compose dp via '
                            'fluid.DistributeTranspiler inside '
                            'transpiler_fn instead')
                    transpiler_fn(self.train_program)
                    # the for_test clone was taken before the hook ran
                    # (reference ordering); carry the mesh annotations over
                    # so test() runs against the same mesh-placed scope
                    dc = getattr(self.train_program, '_dist_config', None)
                    if dc is not None:
                        self.test_program._dist_config = dict(dc)
                        self.test_program._dist_mesh = None
                    # GSPMD annotation path: a hook that set_mesh() the
                    # train program must leave test() on the same mesh —
                    # the scope's persistables are mesh-placed
                    ma = getattr(self.train_program, '_mesh_axes', None)
                    if (ma is not None and getattr(
                            self.test_program, '_mesh_axes', None) is None):
                        self.test_program.set_mesh(
                            list(ma),
                            data_axis=self.train_program._mesh_data_axis)
                    self.train_program._retranspile_pipeline(
                        self.test_program)

        self.place = check_and_get_place(place)
        self.exe = Executor(self.place)
        with self._prog_and_scope_guard():
            self.exe.run(self.startup_program)

        self._serial = 0
        if self.checkpoint_cfg:
            self._maybe_resume_from_checkpoint()

        if param_path and os.path.isdir(param_path):
            with self._prog_and_scope_guard():
                io.load_params(self.exe, param_path,
                               main_program=self.train_program)

    # -- checkpoint/resume ------------------------------------------------

    def _use_sharded_ckpt(self):
        """Annotated (set_mesh) programs checkpoint SHARDED through
        utils.checkpoint.save_sharded: state_dict walks the mesh-placed
        persistables and each host writes only the shards it addresses —
        the dense io.save_checkpoint path would gather a vocab-sharded
        table whole on this host, undoing the sharding's footprint win
        (docs/robustness.md#elastic)."""
        from .executor import _is_annotated
        return _is_annotated(self.train_program)

    def _mesh_axes_list(self):
        mesh = getattr(self.train_program, '_dist_mesh', None)
        if not mesh:
            return None
        return [[str(n), int(s)] for n, s in
                zip(mesh.axis_names, mesh.devices.shape)]

    def _maybe_resume_from_checkpoint(self):
        cfg = self.checkpoint_cfg
        if not os.path.isdir(cfg.checkpoint_dir):
            return
        if self._use_sharded_ckpt():
            from ..utils import checkpoint as shck
            if shck.latest_step(cfg.checkpoint_dir) is not None \
                    and self._resume_sharded(cfg):
                return
            # fall through: no (intact) sharded serial — old dense
            # serials from a pre-elastic run still resume below
        # Newest first; a serial with a torn meta.json / missing or
        # CRC-mismatched params file (crash mid-save, bit rot) falls back
        # to the previous intact one — LOUDLY, because silently replaying
        # steps from an older snapshot is a surprise worth explaining.
        for serial in io.list_checkpoint_serials(cfg.checkpoint_dir)[::-1]:
            try:
                with self._prog_and_scope_guard():
                    with obs.span('trainer.checkpoint.load', serial=serial):
                        meta = io.load_checkpoint(
                            self.exe, cfg.checkpoint_dir, serial=serial,
                            main_program=self.train_program)
            except (RuntimeError, OSError, ValueError, KeyError) as e:
                import warnings
                obs.counter('trainer.resume.fallbacks').inc()
                obs.event('trainer.resume.fallback', serial=serial,
                          error='%s: %s' % (type(e).__name__, e))
                warnings.warn(
                    'checkpoint serial %d in %r failed to load (%s) — '
                    'falling back to the previous serial'
                    % (serial, cfg.checkpoint_dir, e), RuntimeWarning)
                continue
            args = meta.get('trainer_args') or {}
            cfg.load_serial = meta.get('step', 0)
            cfg.epoch_id = int(args.get('epoch_id', 0))
            cfg.step_id = int(args.get('step_id', 0))
            self._serial = int(meta.get('step', 0))
            self._stream_resume_vocab = args.get('streaming_vocab')
            return

    @staticmethod
    def _max_disk_serial(cfg):
        """Largest serial number any sharded_<n>[.tmp|.old] dir under the
        checkpoint dir claims — 0 when none."""
        best = 0
        if os.path.isdir(cfg.checkpoint_dir):
            for d in os.listdir(cfg.checkpoint_dir):
                m = re.fullmatch(r'sharded_(\d+)(\.tmp|\.old)?', d)
                if m:
                    best = max(best, int(m.group(1)))
        return best

    def _resume_sharded(self, cfg):
        """Elastic resume (docs/robustness.md#elastic): restore the
        newest COMMITTED, integrity-verified sharded serial, resharding
        every persistable onto THIS run's mesh — the checkpoint may have
        been written on a different topology (8 devices before a host
        died, 4 now). Exact-step semantics are the dense path's: the
        meta records (epoch, step-within-epoch) and the train loop
        fast-forwards the reader past the already-done steps. Returns
        False (loudly) when no intact sharded serial restores, so the
        caller can try the legacy dense serials."""
        import warnings
        from ..utils import checkpoint as shck
        try:
            with self._prog_and_scope_guard():
                with obs.span('trainer.checkpoint.load', sharded=True):
                    mesh = self.exe._ensure_dist_placement(
                        self.train_program, self.scope)
                    arrays, meta = shck.load_latest_verified(
                        cfg.checkpoint_dir, mesh=mesh)
                    self.exe.load_state_dict(
                        arrays, self.train_program, scope=self.scope)
        except (RuntimeError, OSError, ValueError, KeyError) as e:
            obs.counter('trainer.resume.fallbacks').inc()
            obs.event('trainer.resume.fallback', serial='sharded',
                      error='%s: %s' % (type(e).__name__, e))
            warnings.warn(
                'sharded checkpoint resume from %r failed (%s) — trying '
                'the dense checkpoint serials'
                % (cfg.checkpoint_dir, e), RuntimeWarning)
            return False
        extra = meta.get('extra') or {}
        args = extra.get('trainer_args') or {}
        self._stream_resume_vocab = extra.get('streaming_vocab')
        cfg.load_serial = int(meta.get('step', 0))
        cfg.epoch_id = int(args.get('epoch_id', 0))
        cfg.step_id = int(args.get('step_id', 0))
        # resume numbering PAST every serial number present on disk —
        # committed, staged (.tmp) or demoted (.old). Reusing a crashed
        # incarnation's serial would reuse its staging dir, whose stale
        # step-matched peer manifests could satisfy the new save's
        # commit wait early (mixed-incarnation checkpoint). Every
        # restarted process derives the same number from the same
        # (quiescent) listing, so the cohort stays in step.
        self._serial = max(int(meta.get('step', 0)),
                           self._max_disk_serial(cfg))
        obs.event('elastic.resume', serial=self._serial,
                  epoch=cfg.epoch_id, step=cfg.step_id,
                  from_mesh=extra.get('mesh_axes'),
                  to_mesh=self._mesh_axes_list())
        return True

    def _save_sharded(self, epoch_id, step_id, preempted=False,
                      commit_timeout=None, sync=None):
        """The annotated-program save path: Executor.state_dict walks
        the mesh-placed persistables (a vocab-sharded table stays 8
        device shards — never gathered dense) and save_sharded streams
        each host's own shards, staging + manifest-last + atomic rename
        so a SIGKILL can never leave a latest-looking torn serial. The
        extra meta records the reader position (epoch, step-within-
        epoch) and the mesh shape, for exact-step topology-aware
        resume.

        sync=None follows CheckpointConfig.async_save; emergency paths
        pass sync=True. The async path (docs/perf.md#overlap) pays only
        the buffer snapshot at the step boundary — file IO and the
        commit protocol run on save_sharded_async's writer thread; the
        previous save's handle is drained first, so writers to one dir
        never overlap."""
        from ..utils import checkpoint as shck
        cfg = self.checkpoint_cfg
        if sync is None:
            sync = not getattr(cfg, 'async_save', False)
        args = {'epoch_id': epoch_id, 'step_id': step_id}
        if preempted:
            args['preempted'] = True
        ct = cfg.commit_timeout if commit_timeout is None else commit_timeout
        dest = os.path.join(cfg.checkpoint_dir, 'sharded_%d' % self._serial)
        meta = {'trainer_args': args, 'trainer_id': self.trainer_id,
                'mesh_axes': self._mesh_axes_list()}
        vocab_meta = self._vocab_meta()
        if vocab_meta is not None:
            meta['streaming_vocab'] = vocab_meta
        if not sync:
            # drain the previous writer BEFORE state_dict: ~0 wait in
            # steady state (the write finished steps ago), and it keeps
            # exactly one writer per checkpoint dir
            self._wait_async_ckpt()
        with self._prog_and_scope_guard():
            state = self.exe.state_dict(self.train_program,
                                        scope=self.scope)
            if sync:
                path = shck.save_sharded(dest, state, step=self._serial,
                                         extra_meta=meta,
                                         commit_timeout=ct)
            else:
                self._async_ckpt = shck.save_sharded_async(
                    dest, state, step=self._serial, extra_meta=meta,
                    commit_timeout=ct)
                return dest
        self._prune_sharded(cfg)
        return path

    def _wait_async_ckpt(self, final=False):
        """Drain the in-flight async sharded save (no-op when none).
        Steady state this wait is ~0 — the writer finished during the
        intervening steps; the span records whatever it actually was.
        A CommitTimeout or IO failure here is the PERIODIC-save posture
        (a missed checkpoint, not a dead run): warn loudly, keep
        training on the previous committed serial."""
        h = self._async_ckpt
        if h is None:
            return
        self._async_ckpt = None
        import warnings
        from ..utils.checkpoint import CommitTimeout
        with obs.span('trainer.checkpoint.async_wait',
                      ready=h.done(), final=final):
            try:
                h.wait()
            except CommitTimeout as e:
                warnings.warn(
                    'async sharded checkpoint did not commit (%s); '
                    'training continues on the previous committed '
                    'serial' % e, RuntimeWarning)
                return
            except Exception as e:
                obs.counter('trainer.async_ckpt.failures').inc()
                obs.event('trainer.async_ckpt.failure',
                          error='%s: %s' % (type(e).__name__, e))
                warnings.warn(
                    'async sharded checkpoint FAILED in the background '
                    '(%s: %s) — the serial is missing or partial; '
                    'training continues on the previous committed '
                    'serial' % (type(e).__name__, e), RuntimeWarning)
                return
        self._prune_sharded(self.checkpoint_cfg)

    def _prune_sharded(self, cfg):
        """Keep max_num_checkpoints committed sharded serials (process 0
        only on multi-process meshes — one pruner). Staging leftovers of
        pruned serials go with them."""
        import shutil
        import jax
        if jax.process_index() != 0:
            return
        from ..utils import checkpoint as shck
        serials = []
        for d in os.listdir(cfg.checkpoint_dir):
            m = re.fullmatch(r'sharded_(\d+)', d)
            if m:
                serials.append(int(m.group(1)))
        for s in sorted(serials)[:-cfg.max_num_checkpoints]:
            base = os.path.join(cfg.checkpoint_dir, 'sharded_%d' % s)
            shutil.rmtree(base, ignore_errors=True)
            shutil.rmtree(shck._staging_dir(base), ignore_errors=True)
            shutil.rmtree(base + shck._OLD_SUFFIX, ignore_errors=True)

    def _vocab_meta(self):
        """JSON-able {feed name: VocabTable.state_dict()} of the active
        streaming vocabs (None outside train_stream) — folded into
        every checkpoint's meta so exact-step resume holds under vocab
        drift: the restored map reproduces the id->row assignment the
        restored table rows were trained under
        (docs/embedding.md "streaming ids")."""
        if not self._stream_vocabs:
            return None
        return {str(k): vt.state_dict()
                for k, vt in self._stream_vocabs.items()}

    def _dense_trainer_args(self, epoch_id, step_id, **extra):
        args = {'epoch_id': epoch_id, 'step_id': step_id}
        args.update(extra)
        vm = self._vocab_meta()
        if vm is not None:
            args['streaming_vocab'] = vm
        return args

    def _save_checkpoint(self, epoch_id, step_id, force=False):
        """force=True skips the interval modulo gate — the bundled loop
        applies its own range-crossing gate (a bundle boundary rarely
        lands exactly ON an interval multiple) and records the bundle's
        LAST step, the state the scope actually holds."""
        cfg = self.checkpoint_cfg
        if force or (epoch_id % cfg.epoch_interval == 0
                     and step_id % cfg.step_interval == 0):
            self._serial += 1
            with obs.span('trainer.checkpoint.save',
                          serial=self._serial, epoch=epoch_id,
                          step=step_id,
                          sharded=self._use_sharded_ckpt()):
                if self._use_sharded_ckpt():
                    from ..utils.checkpoint import CommitTimeout
                    try:
                        self._save_sharded(epoch_id, step_id)
                    except CommitTimeout as e:
                        # a slow-but-alive peer (FS stall, GC pause)
                        # missed the commit window: this is a MISSED
                        # periodic checkpoint, not a dead run — the
                        # previous committed serial still carries any
                        # resume. Killing process 0 here would wedge
                        # the healthy peers inside their next
                        # collective. (A genuinely dead peer surfaces
                        # through the heartbeat gate instead.)
                        import warnings
                        warnings.warn(
                            'periodic sharded checkpoint did not '
                            'commit (%s); training continues on the '
                            'previous committed serial' % e,
                            RuntimeWarning)
                    return
                with self._prog_and_scope_guard():
                    io.save_checkpoint(
                        self.exe, cfg.checkpoint_dir,
                        trainer_id=self.trainer_id,
                        main_program=self.train_program,
                        step=self._serial,
                        trainer_args=self._dense_trainer_args(
                            epoch_id, step_id),
                        max_num_checkpoints=cfg.max_num_checkpoints)

    def _save_emergency_checkpoint(self, epoch_id, step_id,
                                   commit_timeout=None):
        """Preemption flush: unconditional (interval-ignoring) snapshot
        recording the exact (epoch, step) just completed, so a successor
        Trainer resumes at step_id + 1 — the reference's crash-recovery
        dirs never had a clean-shutdown writer; SIGTERM simply killed the
        process and lost everything since the last periodic snapshot.
        Annotated programs flush SHARDED, like the periodic path;
        commit_timeout shortens the commit wait when a peer is already
        known dead (host loss)."""
        cfg = self.checkpoint_cfg
        if not cfg:
            return None
        self._serial += 1
        with obs.span('trainer.checkpoint.emergency_flush',
                      serial=self._serial, epoch=epoch_id,
                      step=step_id, sharded=self._use_sharded_ckpt()):
            if self._use_sharded_ckpt():
                # drain any in-flight async writer, then flush
                # SYNCHRONOUSLY: the process is about to exit, and the
                # flush must commit (or stage loudly) before it does
                self._wait_async_ckpt(final=True)
                return self._save_sharded(epoch_id, step_id,
                                          preempted=True,
                                          commit_timeout=commit_timeout,
                                          sync=True)
            with self._prog_and_scope_guard():
                return io.save_checkpoint(
                    self.exe, cfg.checkpoint_dir,
                    trainer_id=self.trainer_id,
                    main_program=self.train_program,
                    step=self._serial,
                    trainer_args=self._dense_trainer_args(
                        epoch_id, step_id, preempted=True),
                    max_num_checkpoints=cfg.max_num_checkpoints)

    # -- preemption -------------------------------------------------------

    def _on_preempt_signal(self, signum, frame):
        # absolutely minimal: flag only. The loop (not the signal frame)
        # owns checkpointing — saving from here could re-enter numpy/jax
        # mid-step.
        self._preempt_requested = True
        self._preempt_signum = signum

    @contextlib.contextmanager
    def _preemption_handlers(self):
        """Install SIGTERM/SIGINT handlers for the duration of train(),
        restoring the previous handlers after. Signals can only be bound
        from the main thread; elsewhere (tests driving trainers from
        worker threads) preemption still works via request_preemption()."""
        import signal as _signal
        import threading
        installed = {}
        if threading.current_thread() is threading.main_thread():
            for sig in (_signal.SIGTERM, _signal.SIGINT):
                try:
                    installed[sig] = _signal.signal(
                        sig, self._on_preempt_signal)
                except (ValueError, OSError):
                    pass
        try:
            yield
        finally:
            for sig, prev in installed.items():
                try:
                    _signal.signal(sig, prev)
                except (ValueError, OSError):
                    pass

    def request_preemption(self):
        """Programmatic preemption (what the SIGTERM handler does): finish
        the in-flight step, flush an emergency checkpoint, return from
        train() cleanly with self.preempted = True."""
        self._preempt_requested = True

    def _finish_preemption(self, last_done):
        """Flush the emergency checkpoint for the last COMPLETED step (if
        any completed this run — otherwise prior checkpoints already
        reflect the state) and mark the trainer preempted."""
        import warnings
        cfg = self.checkpoint_cfg
        saved = False
        if last_done is not None and cfg:
            self._save_emergency_checkpoint(*last_done)
            saved = True
        self.preempted = True
        obs.counter('trainer.preemptions').inc()
        obs.event('trainer.preempted',
                  signum=self._preempt_signum or 'requested',
                  epoch=last_done[0] if last_done else None,
                  step=last_done[1] if last_done else None,
                  emergency_checkpoint=saved)
        where = ('at epoch %d step %d' % last_done if last_done is not None
                 else 'before any step completed')
        if saved:
            detail = 'emergency checkpoint flushed'
        elif cfg:
            detail = ('no emergency checkpoint needed (prior serials '
                      'already reflect the state)')
        else:
            detail = ('emergency checkpoint SKIPPED (no CheckpointConfig '
                      '— progress is lost)')
        warnings.warn(
            'preemption (%s) %s: %s; train() returning cleanly'
            % (self._preempt_signum or 'requested', where, detail),
            RuntimeWarning)

    def _clean_checkpoint(self):
        # Remove only the serial subdirs we created (dense checkpoint_<n>,
        # sharded sharded_<n> + their .tmp staging leftovers) — the
        # configured dir may be (and defaults to) the user's cwd.
        # An in-flight async writer must finish first: deleting dirs out
        # from under it would race the commit rename.
        import shutil
        self._wait_async_ckpt(final=True)
        d = self.checkpoint_cfg.checkpoint_dir
        if not os.path.isdir(d):
            return
        for sub in os.listdir(d):
            if re.fullmatch(r'(checkpoint|sharded)_\d+(\.tmp|\.old)?', sub):
                shutil.rmtree(os.path.join(d, sub), ignore_errors=True)

    # -- host-failure detection -------------------------------------------

    def _check_host_loss(self, last_done, window=None):
        """Heartbeat gate, run at every step boundary BEFORE the next
        dispatch (a dispatch against a dead peer hangs in the
        collective). A stale peer: drain in-flight work, flush an
        emergency checkpoint (sharded saves may legitimately fail to
        COMMIT here — the dead peer can't stage its manifest; the last
        periodic serial then carries the resume), record host_lost, and
        raise the typed parallel.HostLost so the supervisor restarts on
        the surviving topology (docs/robustness.md#elastic)."""
        hb = self.heartbeat
        if hb is None:
            return
        stale = hb.check(raise_error=False)
        if not stale:
            return
        import warnings
        from ..parallel.heartbeat import HostLost
        if window:
            self._drain_async_window(window)
        obs.event('elastic.host_lost', stale=[int(s) for s in stale],
                  epoch=last_done[0] if last_done else None,
                  step=last_done[1] if last_done else None,
                  mesh=self._mesh_axes_list())
        saved = None
        if self.checkpoint_cfg and last_done is not None:
            try:
                saved = self._save_emergency_checkpoint(
                    *last_done,
                    commit_timeout=max(1.0, hb.timeout))
            except Exception as e:
                warnings.warn(
                    'emergency checkpoint after host loss did not '
                    'commit (%s: %s) — resume will fall back to the '
                    'last committed serial' % (type(e).__name__, e),
                    RuntimeWarning)
        # "saved" from a non-zero process means STAGED only — process 0
        # performs the commit rename, and on this path process 0 may be
        # the dead host. Report commitment from the filesystem truth.
        committed = bool(saved) and os.path.isdir(saved)
        self.host_lost = {'stale': list(stale), 'last_done': last_done,
                          'emergency_checkpoint':
                              saved if committed else None,
                          'emergency_staged': saved}
        warnings.warn(
            'host(s) %s lost (heartbeat stale > %.1fs)%s — raising '
            'HostLost; restart on the surviving topology and resume '
            'from the last verified checkpoint'
            % (stale, hb.timeout,
               '; emergency checkpoint committed' if committed
               else '; emergency flush did not commit'), RuntimeWarning)
        raise HostLost(
            'host(s) %s stopped heartbeating during training%s'
            % (stale, ' (last completed step: epoch %d step %d)'
               % last_done if last_done else ''), stale=stale)

    # -- public API -------------------------------------------------------

    def stop(self):
        """reference trainer.py:373 — stop training at the next step."""
        self.__stop = True

    def train(self, num_epochs, event_handler, reader=None, feed_order=None):
        """reference trainer.py:379. While the loop runs, SIGTERM/SIGINT
        mean PREEMPTION, not crash: the in-flight step completes, an
        emergency checkpoint flushes, and train() returns cleanly with
        self.preempted = True (resume by constructing a new Trainer over
        the same checkpoint dir)."""
        self.preempted = False
        self._preempt_requested = False
        started_hb = False
        if self.heartbeat is not None and not self.heartbeat.running:
            self.heartbeat.start()
            started_hb = True
        try:
            with self._preemption_handlers():
                if self.parallel:
                    with self._prog_and_scope_guard():
                        pe = self._get_or_create_parallel_executor()
                    self._train_loop(pe, num_epochs, event_handler, reader,
                                     feed_order)
                else:
                    self._train_loop(self.exe, num_epochs, event_handler,
                                     reader, feed_order)
        finally:
            # train() returning means every checkpoint it started is
            # committed (or loudly failed) — an async writer must never
            # outlive the loop that owns its scope arrays
            self._wait_async_ckpt(final=True)
            if started_hb:
                self.heartbeat.stop()

    def train_stream(self, reader, event_handler=None, feed_order=None,
                     vocabs=None, publisher=None, max_steps=None):
        """Online training over an UNBOUNDED stream — the loop the
        reference's pserver async-training era served, TPU-native
        (docs/embedding.md "streaming ids"). `reader` is an ordinary
        batch-reader factory with NO epoch length: the loop runs until
        the stream ends, `stop()` is called, `max_steps` batches have
        run this call, preemption lands (emergency checkpoint + clean
        return, exactly like train()), or the heartbeat detects a host
        loss (typed HostLost).

        vocabs: {id feed name: streaming.VocabTable} — each named feed
        is translated raw-id -> row on the input stage (prefetch worker
        when double_buffer=True), rows referenced by the in-flight
        batch are pinned until its step completes, and evicted rows are
        zeroed (table + optimizer moments, streaming.RowResetter) at
        the step boundary BEFORE their new owner trains. Translation is
        pure host-side indexing: the compiled step signature never
        changes as the vocab drifts, and with an identity map the
        trained state is bit-exact vs the un-streamed loop (drilled).
        The vocab serializes into every checkpoint's meta and a resumed
        Trainer restores it here, so exact-step resume holds under
        drift.

        publisher: a streaming.DeltaPublisher — after each step the
        touched-row set (StepArtifact.touched_rows: host-side, off the
        step path) is collected, and the publisher's cadence pushes
        those rows' live values into the serving replicas
        (Router.push_deltas). Publisher failures other than the typed
        HostLost are warned and retried next cadence — freshness
        degrades, training never dies for a serving-side hiccup.

        Checkpoints follow CheckpointConfig's step_interval AND
        wallclock_interval_s (whichever fires first); epoch_id is
        recorded as 0 and serials are NOT cleaned on return — a stream
        has no "finished" state, the next Trainer resumes. There is no
        reader fast-forward on resume: a live stream is not replayable;
        the restored (vocab, table, moments) state carries the
        continuity. Returns the number of steps run this call."""
        import time as _time
        if self.parallel:
            raise ValueError('train_stream drives the single-program '
                             'Executor loop; parallel=True does not '
                             'compose with it (use GSPMD annotations)')
        if self.bundle_steps > 1 or self.sync == 'async':
            raise ValueError(
                'train_stream paces checkpoints, vocab leases, and '
                'delta publishing per STEP; bundle_steps>1 / '
                "sync='async' pipeline across steps — pick one "
                '(double_buffer=True overlaps the input side instead)')
        if event_handler is None:
            event_handler = lambda ev: None  # noqa: E731
        vocabs = dict(vocabs or {})
        self._stream_vocabs = vocabs
        cfg = self.checkpoint_cfg
        resumed = bool(cfg and cfg.load_serial)
        if vocabs and resumed and self._stream_resume_vocab:
            for fname, state in self._stream_resume_vocab.items():
                if fname in vocabs:
                    vocabs[fname].load_state_dict(state)
            # one-shot: a SECOND train_stream() call on this Trainer
            # continues the LIVE (drifted) vocab — re-applying the
            # checkpoint-time map would silently mis-map ids to rows
            self._stream_resume_vocab = None
        from ..streaming.vocab import RowResetter, table_state_names
        resetter = RowResetter()
        reset_names = {}
        for fname, vt in vocabs.items():
            if vt.table:
                reset_names[fname] = table_state_names(
                    self.train_program, vt.table)
                if hasattr(vt, 'validate_program'):
                    # tiered tables refuse a dim-sharded table TYPED
                    # (a spill would tear rows across hosts) — before
                    # any step runs, not on the first eviction
                    vt.validate_program(self.train_program)

        leases = {}   # step_id -> [Lease] (writer: input stage;
        #               reader: the loop after that step completes)

        def translate(step_id, fed):
            ls = []
            for fname, vt in vocabs.items():
                v = fed.get(fname)
                if v is None:
                    continue
                if not hasattr(v, 'dtype'):
                    raise TypeError(
                        'train_stream vocab feed %r is not a dense '
                        'array (got %r) — streaming ids are dense id '
                        'batches' % (fname, type(v).__name__))
                mapped, lease = vt.translate(v)
                fed[fname] = mapped.astype(v.dtype, copy=False)
                ls.append(lease)
            if ls:
                leases[step_id] = ls
            return fed

        def apply_resets():
            # zero evicted rows (table + moments) BEFORE the step that
            # trains their new owners dispatches — stale moments would
            # bleed the previous occupant's history into the new id.
            # A tiered table (embedding.tiers.TieredVocabTable) owns
            # its boundary instead: evictions SPILL to the host arena,
            # warm re-admissions RESTORE — and it reports the rows it
            # mutated so the delta publisher keeps serving replicas
            # converged across a spill/restore cycle.
            changed = None
            for fname, vt in vocabs.items():
                names = reset_names.get(fname)
                if not names:
                    continue
                if hasattr(vt, 'apply_step_boundary'):
                    ch = vt.apply_step_boundary(
                        self.scope._chain_get, self.scope._chain_set,
                        names)
                    if ch:
                        changed = changed or {}
                        for t, rows in ch.items():
                            prev = changed.get(t)
                            if prev is None:
                                changed[t] = rows
                            else:
                                changed[t] = sorted(
                                    {int(r) for r in prev}
                                    | {int(r) for r in rows})
                    continue
                rows = vt.drain_resets()
                if not rows:
                    continue
                arrays = [self.scope._chain_get(n) for n in names]
                new = resetter.reset(arrays, rows)
                for n, a in zip(names, new):
                    self.scope._chain_set(n, a)
            return changed

        steps_run = 0
        started_hb = False
        if self.heartbeat is not None and not self.heartbeat.running:
            self.heartbeat.start()
            started_hb = True
        self.preempted = False
        self._preempt_requested = False
        last_done = None
        last_ckpt_t = _time.monotonic()
        start_step = cfg.step_id + 1 if resumed else 0
        warned_dense = set()
        try:
            with self._preemption_handlers():
                with self._prog_and_scope_guard():
                    feed_vars = build_feed_var_list(self.train_program,
                                                    feed_order)
                    feeder = DataFeeder(feed_list=feed_vars,
                                        place=self.place)
                    fetch = [v.name for v in self.train_func_outputs]
                    it = self._iter_staged(reader, feeder, post=translate)
                    self._stream_it = it
                    for rel_id, fed in it:
                        step_id = start_step + rel_id
                        if self.__stop or (max_steps is not None
                                           and steps_run >= max_steps):
                            return steps_run
                        if self._preempt_requested:
                            self._finish_preemption(last_done)
                            return steps_run
                        self._check_host_loss(last_done)
                        tier_changed = apply_resets()
                        begin = BeginStepEvent(0, step_id)
                        event_handler(begin)
                        want = fetch if begin.fetch_metrics else []
                        self._steps_run = getattr(self, '_steps_run',
                                                  0) + 1
                        with obs.span('trainer.step',
                                      step_num=self._steps_run,
                                      epoch=0, step=step_id, stream=True):
                            metrics = self.exe.run(
                                program=self.train_program, feed=fed,
                                fetch_list=want)
                        last_done = (0, step_id)
                        steps_run += 1
                        for lease in leases.pop(rel_id, []):
                            lease.release()
                        if publisher is not None:
                            self._stream_publish(publisher, fed, want,
                                                 warned_dense, vocabs,
                                                 extra_rows=tier_changed)
                        if cfg:
                            due = (step_id > 0 and step_id
                                   % cfg.step_interval == 0)
                            wall = cfg.wallclock_interval_s
                            if not due and wall is not None:
                                due = (_time.monotonic() - last_ckpt_t
                                       >= wall)
                            if due:
                                self._save_checkpoint(0, step_id,
                                                      force=True)
                                last_ckpt_t = _time.monotonic()
                                for vt in vocabs.values():
                                    if hasattr(vt, 'mark_checkpoint'):
                                        # a committed serial no longer
                                        # references slots released
                                        # before it: recycle the
                                        # arena's limbo list
                                        vt.mark_checkpoint()
                        event_handler(EndStepEvent(0, step_id, metrics))
                        if self._preempt_requested:
                            self._finish_preemption(last_done)
                            return steps_run
                    return steps_run
        finally:
            it = getattr(self, '_stream_it', None)
            self._stream_it = None
            if it is not None:
                it.close()   # unblock the prefetch worker on early exit
            for ls in leases.values():
                for lease in ls:
                    lease.release()
            leases.clear()
            self._wait_async_ckpt(final=True)
            if started_hb:
                self.heartbeat.stop()
            self._stream_vocabs = None
            self._stream_art = None

    def _stream_publish(self, publisher, fed, fetch, warned_dense, vocabs,
                        extra_rows=None):
        """Collect this step's touched rows (host-side seam) and run the
        publisher's cadence. `extra_rows` ({table: rows}) carries rows
        the TIER boundary mutated outside the batch — zeroed on spill,
        scattered on restore — so serving replicas converge on them
        too. Serving-side failures warn and retry next cadence; the
        typed HostLost propagates — that is a pod event, not a
        publishing hiccup."""
        import warnings
        from ..parallel.heartbeat import HostLost
        # resolve the artifact ONCE per fetch set, not per step:
        # Executor.step_artifact runs the _prepare front half, which
        # re-places the whole feed batch on device — per-step that
        # would double the hot loop's host->device traffic just to
        # read metadata (the sparse plan does not depend on the batch)
        art = getattr(self, '_stream_art', None)
        if art is None or self._stream_art_key != tuple(fetch):
            try:
                art = self.exe.step_artifact(self.train_program, fed,
                                             fetch, scope=self.scope)
            except Exception as e:
                warnings.warn('train_stream: could not resolve the step '
                              'artifact for touched-row collection '
                              '(%s: %s)' % (type(e).__name__, e),
                              RuntimeWarning)
                return
            self._stream_art = art
            self._stream_art_key = tuple(fetch)
        for fname, vt in vocabs.items():
            t = vt.table
            if t and t not in art.sparse_plan and t not in warned_dense:
                warned_dense.add(t)
                warnings.warn(
                    'train_stream: table %r (vocab feed %r) is NOT on '
                    'the sparse update path — its update writes every '
                    'row each step, so touched-row deltas under-report '
                    'and row eviction is unsafe. Build the lookup with '
                    'is_sparse=True (docs/embedding.md)' % (t, fname),
                    RuntimeWarning)
        touched = art.touched_rows(fed)
        if extra_rows:
            import numpy as _np
            touched = dict(touched or {})
            for t, rows in extra_rows.items():
                merged = {int(r) for r in rows}
                prev = touched.get(t)
                if prev is not None:
                    merged.update(
                        int(r) for r in _np.asarray(prev).reshape(-1))
                touched[t] = _np.asarray(sorted(merged), _np.int64)
        if touched:
            publisher.collect(touched)
        try:
            publisher.maybe_publish(
                lambda name: self.scope._chain_get(name))
        except HostLost:
            raise
        except Exception as e:
            obs.counter('streaming.push_failures').inc()
            warnings.warn(
                'train_stream: delta push failed (%s: %s) — deltas are '
                'retained and retried at the next cadence'
                % (type(e).__name__, e), RuntimeWarning)

    def test(self, reader, feed_order=None):
        """reference trainer.py:409 — mean of train_func outputs over the
        test reader, on the for_test clone."""
        with scope_guard(self.scope):
            feed_vars = build_feed_var_list(self.test_program, feed_order)
            feeder = DataFeeder(feed_list=feed_vars, place=self.place)
            fetch = [v.name for v in self.train_func_outputs]
            import numpy as np
            accumulated = [0.0] * len(fetch)
            count = 0
            for data in reader():
                outs = self.exe.run(program=self.test_program,
                                    feed=feeder.feed(data), fetch_list=fetch)
                accumulated = [a + float(np.asarray(o).reshape(-1)[0])
                               for a, o in zip(accumulated, outs)]
                count += 1
            return [a / max(count, 1) for a in accumulated]

    def save_params(self, param_path):
        """reference trainer.py:421."""
        with self._prog_and_scope_guard():
            io.save_params(self.exe, dirname=param_path,
                           main_program=self.train_program)

    def save_inference_model(self, param_path, feeded_var_names,
                             target_var_indexes):
        """Persist the pruned inference graph + params (reference
        trainer.py save_inference_model variant)."""
        with self._prog_and_scope_guard():
            io.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, main_program=self.train_program)

    # -- internals --------------------------------------------------------

    @contextlib.contextmanager
    def _prog_and_scope_guard(self):
        with framework.program_guard(main_program=self.train_program,
                                     startup_program=self.startup_program):
            with scope_guard(self.scope):
                yield

    def _get_or_create_parallel_executor(self):
        if getattr(self, 'parallel_executor', None) is None:
            self.parallel_executor = parallel_executor.ParallelExecutor(
                use_cuda=False,
                loss_name=self.train_func_outputs[0].name,
                main_program=self.train_program, scope=self.scope)
        return self.parallel_executor

    @staticmethod
    def _bundle_feed_sig(fed):
        """Shape/dtype signature of one fed batch — bundles may only
        group batches that share it (one compiled module)."""
        from .executor import _feed_signature
        return tuple(sorted(_feed_signature(n, v) for n, v in fed.items()))

    def _drain_async_window(self, window, n_keep=0):
        """Sync the oldest in-flight steps until at most n_keep remain.
        Each block records executor.host_stall — the histogram that shows
        how much device time the async window actually hid."""
        from .executor import FetchHandle
        while len(window) > n_keep:
            for h in window.popleft():
                if isinstance(h, FetchHandle):
                    h.block()

    def _iter_staged(self, reader, feeder, skip_until=-1, post=None):
        """Yield (step_id, fed_batch) for one epoch's reader pass.

        double_buffer=False: the DataFeeder assembly runs inline (the
        historical behavior), timed as a `trainer.input_stage` span so
        the on/off A/B is measurable from one run log.

        double_buffer=True (docs/perf.md#overlap): assembly — and the
        host->device transfer for plain single-device programs — runs on
        a reader.pipeline.prefetch worker thread, staging batch N+1
        while step N executes. The span then measures only the time the
        loop actually BLOCKED on the queue: ~0 in the overlapped steady
        state (the obs_report step-artifact section computes the overlap
        ratio from input_stage vs trainer.step time). Bundled loops keep
        host ndarrays so run_bundle's single-stack device transfer stays
        on its fast path; mesh programs keep placement in _prepare.

        skip_until: last step id already completed before a crash
        (resume fast-forward) — those reader items are consumed and
        yielded as (step_id, None) WITHOUT feed assembly or
        input_stage accounting, so catching up past N done steps stays
        as cheap as it was before staging existed.

        post(step_id, fed) -> fed: per-batch feed rewrite hook, run on
        the SAME thread as the assembly (the prefetch worker when
        double-buffered, before device staging) — the streaming-ids
        loop translates raw ids through its VocabTable here, so
        admission/eviction overlap the previous step exactly like the
        rest of the input stage (docs/embedding.md "streaming ids")."""
        import time as _time

        def record(step_id, dt, staged):
            obs.span_record('trainer.input_stage', dt, step=step_id,
                            staged=staged)
            self.input_stage_s += dt
            self.batches_fed += 1

        if not self.double_buffer:
            def plain():
                for step_id, data in enumerate(reader()):
                    if step_id <= skip_until:
                        yield step_id, None
                        continue
                    t0 = _time.perf_counter()
                    fed = feeder.feed(data)
                    if post is not None:
                        fed = post(step_id, fed)
                    record(step_id, _time.perf_counter() - t0, False)
                    yield step_id, fed
            return plain()

        from ..reader import pipeline as rpipe
        exe, prog = self.exe, self.train_program
        place_in_worker = (not self.parallel and self.bundle_steps == 1
                           and getattr(prog, '_dist_config', None) is None
                           and getattr(prog, '_mesh_axes', None) is None)

        def tagged():
            return enumerate(reader())

        def stage(pair):
            step_id, data = pair
            if step_id <= skip_until:
                return step_id, None
            fed = feeder.feed(data)
            if post is not None:
                fed = post(step_id, fed)
            if place_in_worker:
                fed = exe._place_feed(prog, fed, None)
            return step_id, fed

        staged = rpipe.prefetch(tagged, depth=2, transform=stage)

        def overlapped():
            it = staged()
            try:
                while True:
                    t0 = _time.perf_counter()
                    try:
                        step_id, fed = next(it)
                    except StopIteration:
                        return
                    if fed is not None:
                        record(step_id, _time.perf_counter() - t0, True)
                    yield step_id, fed
            finally:
                it.close()   # unblock the prefetch worker on early exit

        return overlapped()

    def _train_loop(self, exe, num_epochs, event_handler, reader, feed_order):
        with self._prog_and_scope_guard():
            feed_vars = build_feed_var_list(self.train_program, feed_order)
            feeder = DataFeeder(feed_list=feed_vars, place=self.place)
            is_pe = isinstance(exe, parallel_executor.ParallelExecutor)
            fetch = [v.name for v in self.train_func_outputs]
            cfg = self.checkpoint_cfg
            start_epoch = cfg.epoch_id if cfg and cfg.load_serial else 0
            if self.bundle_steps > 1 and not is_pe:
                self._train_loop_bundled(exe, num_epochs, event_handler,
                                         reader, feeder, fetch)
                return
            use_async = self.sync == 'async' and not is_pe
            import collections
            window = collections.deque()   # in-flight async fetch handles
            # (epoch, step) of the last COMPLETED step this run — what an
            # emergency checkpoint must record when preemption is noticed
            # while the reader blocks / between steps, i.e. before another
            # exe.run ever happens
            last_done = None
            for epoch_id in range(start_epoch, num_epochs):
                event_handler(BeginEpochEvent(epoch_id))
                skip = (cfg.step_id if cfg and cfg.load_serial
                        and epoch_id == cfg.epoch_id else -1)
                for step_id, fed in self._iter_staged(reader, feeder,
                                                      skip_until=skip):
                    if self.__stop:
                        self._drain_async_window(window)
                        if cfg:
                            self._clean_checkpoint()
                        return
                    if self._preempt_requested:
                        # signal landed while the reader was producing
                        # this batch (which can block for a long time):
                        # flush NOW from the consistent between-step
                        # state instead of paying for one more step
                        self._drain_async_window(window)
                        self._finish_preemption(last_done)
                        return
                    # host-failure gate: BEFORE dispatching another step
                    # whose collectives would hang on a dead peer
                    self._check_host_loss(last_done, window)
                    if fed is None:
                        continue  # already done before the crash
                    begin = BeginStepEvent(epoch_id, step_id)
                    event_handler(begin)
                    want = fetch if begin.fetch_metrics else []
                    # trainer.step nests the executor.step span and, when
                    # observability is on, marks the XLA trace with
                    # StepTraceAnnotation so Perfetto groups device
                    # activity per training step
                    self._steps_run = getattr(self, '_steps_run', 0) + 1
                    with obs.span('trainer.step',
                                  step_num=self._steps_run,
                                  epoch=epoch_id, step=step_id):
                        if is_pe:
                            metrics = exe.run(want, feed=fed)
                        elif use_async:
                            metrics = exe.run(program=self.train_program,
                                              feed=fed,
                                              fetch_list=want,
                                              sync='async')
                        else:
                            metrics = exe.run(program=self.train_program,
                                              feed=fed,
                                              fetch_list=want)
                    last_done = (epoch_id, step_id)
                    if use_async:
                        # bounded dispatch window: the handler below may
                        # read (sync) its step's metrics or not — either
                        # way at most async_window steps stay un-synced
                        window.append(metrics)
                        self._drain_async_window(window,
                                                 n_keep=self.async_window)
                    if self._preempt_requested:
                        # the step above COMPLETED (run() synchronizes on
                        # its fetches; async handles sync on read); record
                        # it and leave. No _clean_checkpoint: the whole
                        # point is resuming.
                        self._drain_async_window(window)
                        self._finish_preemption(last_done)
                        event_handler(EndStepEvent(epoch_id, step_id,
                                                   metrics))
                        return
                    if cfg:
                        self._save_checkpoint(epoch_id, step_id)
                    event_handler(EndStepEvent(epoch_id, step_id, metrics))
                event_handler(EndEpochEvent(epoch_id))
                if self._preempt_requested:
                    # between epochs: same flush, no extra step
                    self._drain_async_window(window)
                    self._finish_preemption(last_done)
                    return
            self._drain_async_window(window)
            if cfg:
                self._clean_checkpoint()

    def _train_loop_bundled(self, exe, num_epochs, event_handler, reader,
                            feeder, fetch):
        """K-step bundled hot loop: buffer K reader batches, run them as
        ONE Executor.run_bundle dispatch, then fire the K EndStepEvents
        with per-step metric slices. Stop/preemption are honored at
        bundle boundaries (a partial buffer is flushed first, so no
        consumed batch is silently dropped); periodic checkpoints are
        taken after a bundle for its LAST step — the scope only ever
        holds bundle-end state."""
        import numpy as np
        K = self.bundle_steps
        cfg = self.checkpoint_cfg
        start_epoch = cfg.epoch_id if cfg and cfg.load_serial else 0
        last_done = None

        def bundle_checkpoint(first_step, done):
            """Periodic-checkpoint gate for a just-flushed bundle: save
            when ANY step in [first_step, last_step] crossed a
            step_interval mark — the boundary itself rarely lands on a
            multiple (K=8, interval=10 never does), so the unbundled
            modulo gate would silently never fire. Records the bundle's
            last step: that is the state the scope holds."""
            if not cfg or done is None:
                return
            epoch_id, last_step = done
            if epoch_id % cfg.epoch_interval:
                return
            if any(s % cfg.step_interval == 0
                   for s in range(first_step, last_step + 1)):
                self._save_checkpoint(epoch_id, last_step, force=True)

        def run_bundle_buf(buf, epoch_id):
            """Execute buffered (step_id, feed, want) entries; returns the
            last (epoch, step) done."""
            if not buf:
                return None
            want = buf[0][2]   # fetch_metrics decided per bundle
            feeds = [b[1] for b in buf]
            self._steps_run = getattr(self, '_steps_run', 0) + len(buf)
            with obs.span('trainer.step', step_num=self._steps_run,
                          epoch=epoch_id, step=buf[-1][0],
                          bundle_steps=len(buf)):
                stacked = exe.run_bundle(program=self.train_program,
                                         feeds=feeds, fetch_list=want)
            for j, (step_id, _f, _w) in enumerate(buf):
                if want:
                    metrics = [m[j] if isinstance(m, list)
                               else np.asarray(m)[j] for m in stacked]
                else:
                    metrics = []
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
            return (epoch_id, buf[-1][0])

        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            buf = []   # (step_id, feed_dict, want) awaiting one dispatch
            buf_sig = None
            skip = (cfg.step_id if cfg and cfg.load_serial
                    and epoch_id == cfg.epoch_id else -1)
            for step_id, fed in self._iter_staged(reader, feeder,
                                                  skip_until=skip):
                if self.__stop:
                    done = run_bundle_buf(buf, epoch_id)
                    last_done = done or last_done
                    if cfg:
                        self._clean_checkpoint()
                    return
                if self._preempt_requested:
                    done = run_bundle_buf(buf, epoch_id)
                    last_done = done or last_done
                    self._finish_preemption(last_done)
                    return
                # host-failure gate; buffered batches are NOT flushed
                # through the mesh first (its peers are gone) — the
                # emergency path records the last COMPLETED bundle
                self._check_host_loss(last_done)
                if fed is None:
                    continue  # already done before the crash
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                sig = self._bundle_feed_sig(fed)
                if buf and sig != buf_sig:
                    # batch shape changed mid-stream (classically: the
                    # reader's short last batch) — a bundle is one
                    # compiled module over uniform shapes, so flush what
                    # is buffered and start a new bundle
                    first = buf[0][0]
                    done = run_bundle_buf(buf, epoch_id)
                    last_done = done or last_done
                    buf = []
                    if not self._preempt_requested:
                        bundle_checkpoint(first, done)
                buf_sig = sig
                # fetch set is per BUNDLE (one compiled module): the first
                # buffered step's fetch_metrics decision wins
                want = (buf[0][2] if buf
                        else (fetch if begin.fetch_metrics else []))
                buf.append((step_id, fed, want))
                if len(buf) == K:
                    first = buf[0][0]
                    done = run_bundle_buf(buf, epoch_id)
                    last_done = done or last_done
                    buf = []
                    if self._preempt_requested:
                        self._finish_preemption(last_done)
                        return
                    bundle_checkpoint(first, done)
            if buf:   # partial bundle at epoch end
                first = buf[0][0]
                done = run_bundle_buf(buf, epoch_id)
                last_done = done or last_done
                if not self._preempt_requested:
                    bundle_checkpoint(first, done)
            event_handler(EndEpochEvent(epoch_id))
            if self._preempt_requested:
                self._finish_preemption(last_done)
                return
        if cfg:
            self._clean_checkpoint()
