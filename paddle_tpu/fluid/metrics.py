"""Host-side metric accumulators. Parity: reference python/paddle/fluid/metrics.py."""
import copy

import numpy as np

__all__ = ['MetricBase', 'CompositeMetric', 'Precision', 'Recall', 'Accuracy',
           'ChunkEvaluator', 'EditDistance', 'DetectionMAP', 'Auc']


def _is_numpy_(var):
    return isinstance(var, (np.ndarray, np.generic))


class MetricBase(object):
    def __init__(self, name=None):
        self._name = str(name) if name is not None else self.__class__.__name__

    def __str__(self):
        return self._name

    def reset(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        for attr, value in states.items():
            if isinstance(value, int):
                setattr(self, attr, 0)
            elif isinstance(value, float):
                setattr(self, attr, .0)
            elif isinstance(value, (np.ndarray, np.generic)):
                setattr(self, attr, np.zeros_like(value))
            else:
                setattr(self, attr, None)

    def get_config(self):
        states = {
            attr: value
            for attr, value in self.__dict__.items()
            if not attr.startswith("_")
        }
        config = {}
        config.update({"name": self._name, "states": copy.deepcopy(states)})
        return config

    def update(self, preds, labels):
        raise NotImplementedError()

    def eval(self):
        raise NotImplementedError()


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super(CompositeMetric, self).__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("metric should be MetricBase")
        self._metrics.append(metric)

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class Precision(MetricBase):
    def __init__(self, name=None):
        super(Precision, self).__init__(name)
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        sample_num = labels.shape[0]
        for i in range(sample_num):
            pred = (preds.reshape(sample_num, -1)[i] > 0.5).astype("int32")
            label = labels.reshape(sample_num, -1)[i]
            if pred == 1:
                if pred == label:
                    self.tp += 1
                else:
                    self.fp += 1

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap != 0 else .0


class Recall(MetricBase):
    def __init__(self, name=None):
        super(Recall, self).__init__(name)
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels)
        sample_num = labels.shape[0]
        for i in range(sample_num):
            pred = (preds.reshape(sample_num, -1)[i] > 0.5).astype("int32")
            label = labels.reshape(sample_num, -1)[i]
            if label == 1:
                if pred == label:
                    self.tp += 1
                else:
                    self.fn += 1

    def eval(self):
        recall = self.tp + self.fn
        return float(self.tp) / recall if recall != 0 else .0


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super(Accuracy, self).__init__(name)
        self.value = .0
        self.weight = .0

    def update(self, value, weight):
        self.value += float(np.asarray(value).reshape(-1)[0]) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: weight is 0 (call update first)")
        return self.value / self.weight


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super(ChunkEvaluator, self).__init__(name)
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = float(self.num_correct_chunks) / self.num_infer_chunks \
            if self.num_infer_chunks else 0.
        recall = float(self.num_correct_chunks) / self.num_label_chunks \
            if self.num_label_chunks else 0.
        f1_score = 2 * precision * recall / (precision + recall) \
            if self.num_correct_chunks else 0.
        return precision, recall, f1_score


class EditDistance(MetricBase):
    def __init__(self, name=None):
        super(EditDistance, self).__init__(name)
        self.total_distance = .0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        distances = np.asarray(distances)
        seq_num = int(np.asarray(seq_num).sum())
        self.seq_num += seq_num
        self.instance_error += int(np.sum(distances > 0))
        self.total_distance += float(np.sum(distances))

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("EditDistance: no data (call update first)")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class DetectionMAP(MetricBase):
    def __init__(self, name=None):
        super(DetectionMAP, self).__init__(name)
        self.has_value = False
        self.value = .0
        self.weight = .0

    def update(self, value, weight=1):
        if not _is_numpy_(np.asarray(value)):
            raise ValueError("value should be numpy-convertible")
        self.value += float(np.asarray(value).reshape(-1)[0])
        self.weight += weight
        self.has_value = True

    def eval(self):
        if self.weight == 0:
            raise ValueError("DetectionMAP: weight is 0")
        return self.value / self.weight


class Auc(MetricBase):
    """Host-side streaming AUC (reference metrics.py:Auc)."""

    def __init__(self, name=None, curve='ROC', num_thresholds=200):
        super(Auc, self).__init__(name)
        self._curve = curve
        self._num_thresholds = num_thresholds
        self.tp_list = np.zeros((num_thresholds,))
        self.fn_list = np.zeros((num_thresholds,))
        self.tn_list = np.zeros((num_thresholds,))
        self.fp_list = np.zeros((num_thresholds,))

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        kepsilon = 1e-7
        thresholds = [(i + 1) * 1.0 / (self._num_thresholds - 1)
                      for i in range(self._num_thresholds - 2)]
        thresholds = [0.0 - kepsilon] + thresholds + [1.0 + kepsilon]
        p1 = preds[:, 1] if preds.ndim == 2 and preds.shape[1] >= 2 \
            else preds.reshape(-1)
        for idx_thresh, thresh in enumerate(thresholds):
            tp = np.sum((labels > 0) & (p1 >= thresh))
            fn = np.sum((labels > 0) & (p1 < thresh))
            tn = np.sum((labels <= 0) & (p1 < thresh))
            fp = np.sum((labels <= 0) & (p1 >= thresh))
            self.tp_list[idx_thresh] += tp
            self.fn_list[idx_thresh] += fn
            self.tn_list[idx_thresh] += tn
            self.fp_list[idx_thresh] += fp

    def eval(self):
        epsilon = 1e-6
        num_thresholds = self._num_thresholds
        tpr = (self.tp_list.astype("float32") +
               epsilon) / (self.tp_list + self.fn_list + epsilon)
        fpr = self.fp_list.astype("float32") / (
            self.fp_list + self.tn_list + epsilon)
        auc_value = 0
        for i in range(num_thresholds - 1):
            dx = fpr[i] - fpr[i + 1]
            y = (tpr[i] + tpr[i + 1]) / 2
            auc_value += dx * y
        return auc_value
