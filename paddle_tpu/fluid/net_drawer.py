"""Draw a Program's op/variable graph as graphviz dot.

Parity: reference python/paddle/fluid/net_drawer.py (draw_graph over
startup+main programs; ops as rects, parameters highlighted)."""
import logging

from . import graphviz

__all__ = ['draw_graph']

logger = logging.getLogger(__name__)

OP_STYLE = dict(shape='rect', style='rounded,filled', fillcolor='lightblue')
VAR_STYLE = dict(shape='box', style='dotted')
PARAM_STYLE = dict(shape='ellipse', style='filled', fillcolor='lightgrey')


def parse_graph(program, graph, var_dict, **kwargs):
    block = program.global_block()
    param_names = {p.name for p in block.all_parameters()}
    for name in block.vars:
        if name not in var_dict:
            style = PARAM_STYLE if name in param_names else VAR_STYLE
            var_dict[name] = graph.add_node(name, prefix='var', **style)
    for op in block.ops:
        op_node = graph.add_node(op.type, prefix='op', **OP_STYLE)
        for _, invars in op.inputs.items():
            for v in invars:
                if v is not None and v.name in var_dict:
                    graph.add_edge(var_dict[v.name], op_node)
        for _, outvars in op.outputs.items():
            for v in outvars:
                if v is not None:
                    if v.name not in var_dict:
                        var_dict[v.name] = graph.add_node(
                            v.name, prefix='var', **VAR_STYLE)
                    graph.add_edge(op_node, var_dict[v.name])


def draw_graph(startup_program, main_program, path='graph.dot', **kwargs):
    """Emit one dot graph covering both programs; returns the dot path."""
    graph = graphviz.Graph('ProgramGraph', rankdir='TB')
    var_dict = {}
    if startup_program is not None:
        parse_graph(startup_program, graph, var_dict)
    if main_program is not None:
        parse_graph(main_program, graph, var_dict)
    graph.compile(path)
    return path
