"""Deprecated Evaluator shims. Parity: reference python/paddle/fluid/evaluator.py
(the reference deprecates these toward fluid.metrics)."""
import warnings

from . import metrics as _metrics

__all__ = ['ChunkEvaluator', 'EditDistance', 'DetectionMAP']


def _deprecated(name):
    warnings.warn("fluid.evaluator.%s is deprecated; use fluid.metrics.%s"
                  % (name, name), DeprecationWarning)


class ChunkEvaluator(_metrics.ChunkEvaluator):
    def __init__(self, *args, **kwargs):
        _deprecated('ChunkEvaluator')
        super(ChunkEvaluator, self).__init__()


class EditDistance(_metrics.EditDistance):
    def __init__(self, *args, **kwargs):
        _deprecated('EditDistance')
        super(EditDistance, self).__init__()


class DetectionMAP(_metrics.DetectionMAP):
    def __init__(self, *args, **kwargs):
        _deprecated('DetectionMAP')
        super(DetectionMAP, self).__init__()
