"""Program / Block / Operator / Variable graph IR.

Parity: reference python/paddle/fluid/framework.py (Variable:142,
Operator:431, Block:855, Program:1339, Parameter:1874).

TPU-first redesign: the reference serializes ops into a protobuf ProgramDesc
interpreted op-by-op by a C++ Executor with per-Place CUDA/CPU kernels. Here
the Program is a lightweight Python-side op list that the Executor lowers in
one pass into a single jitted XLA computation (see executor.py) — ops are
*symbols*, resolved through the lowering registry (ops_impl/) at trace time.
Shape inference runs at graph-build time through jax.eval_shape over the same
lowering rules, so there is exactly one definition of every op's semantics.
"""
import collections
import contextlib
import copy
import os
import sys

import numpy as np

from . import core
from . import unique_name

__all__ = [
    'Program', 'Operator', 'Parameter', 'Variable', 'Block',
    'default_startup_program', 'default_main_program', 'program_guard',
    'name_scope', 'device_guard', 'get_var', 'grad_var_name',
    'strict_infer_shape', 'normalize_sharding',
]

GRAD_VAR_SUFFIX = "@GRAD"
# Mirrors the reference's OpRole attr used to prune backward/optimize ops in
# Program.clone(for_test=True) (framework.py op_role machinery).
ROLE_FORWARD = 0
ROLE_BACKWARD = 1
ROLE_OPTIMIZE = 2
ROLE_LRSCHED = 16
ROLE_METRIC = 32

# A distinctive stand-in for the dynamic batch dim (-1) during build-time
# abstract evaluation; mapped back to -1 in inferred output shapes. A large
# prime so (a) multiples of it can only have come from the stand-in itself
# and (b) no plausible user tensor dim collides with it; Variable.__init__
# rejects the collision outright rather than silently mapping the dim to -1.
DYN_DIM = 999983


def normalize_sharding(spec):
    """Normalize a sharding annotation into the canonical per-dim tuple.

    A spec names, per tensor dimension, the mesh axis (or axes) that
    dimension is partitioned over: each entry is an axis name, None
    (replicated dim), or a tuple of axis names (partitioned over the
    axes' product). Trailing dims may be omitted (replicated). Examples:
    ``('model', None)``, ``('dp',)``, ``(('tp', 'dp'), None)``. A bare
    string means dim 0 over that axis. Returns None for None, else a
    tuple ready for jax.sharding.PartitionSpec(*spec) — framework.py
    itself never imports jax; the Executor builds the NamedSharding."""
    if spec is None:
        return None
    if isinstance(spec, str):
        spec = (spec,)
    if not isinstance(spec, (list, tuple)):
        raise ValueError(
            'sharding must be a tuple of mesh-axis names / None / '
            'axis-name tuples, got %r' % (spec,))
    out = []
    for e in spec:
        if e is None:
            out.append(None)
        elif isinstance(e, str):
            out.append(e)
        elif (isinstance(e, (list, tuple)) and e
              and all(isinstance(a, str) for a in e)):
            out.append(tuple(e))
        else:
            raise ValueError(
                'bad sharding entry %r in %r: each dim is an axis name, '
                'None, or a non-empty tuple of axis names' % (e, spec))
    return tuple(out)


def _sharding_to_jsonable(spec):
    return [list(e) if isinstance(e, tuple) else e for e in spec]


def grad_var_name(name):
    return name + GRAD_VAR_SUFFIX


# -- op provenance (docs/analysis.md) ---------------------------------------
# Every Operator records the user-code callsite that built it (the first
# stack frame OUTSIDE paddle_tpu/fluid), so analyzer findings and strict
# shape-inference errors can say "the op you built at train.py:42" instead
# of naming an anonymous temp var. The sys._getframe walk costs ~1us per op
# at BUILD time only (never on the run path); PADDLE_TPU_PROVENANCE=0
# disables it for build-latency-critical embedders.
ENV_PROVENANCE = 'PADDLE_TPU_PROVENANCE'
_FLUID_DIR = os.path.dirname(os.path.abspath(__file__)) + os.sep


def provenance_enabled():
    return os.environ.get(ENV_PROVENANCE, '1').lower() not in (
        '0', 'off', 'false', 'no')


def _capture_callsite():
    """file:line of the nearest stack frame outside paddle_tpu/fluid (the
    layer call that created the op), or None when disabled/not found."""
    if not provenance_enabled():
        return None
    try:
        f = sys._getframe(2)
    except ValueError:
        return None
    while f is not None:
        fn = f.f_code.co_filename
        if not os.path.abspath(fn).startswith(_FLUID_DIR):
            return '%s:%d' % (fn, f.f_lineno)
        f = f.f_back
    return None


# -- strict shape inference --------------------------------------------------
# Default: append_op's build-time inference is best-effort (a rule that
# cannot abstract-eval leaves the declared shapes alone). Under strict mode
# a FAILING rule raises lowering.InferShapeError naming the op type and its
# build callsite — the loud contract layers opt into and tests drill.
ENV_STRICT_INFER = 'PADDLE_TPU_STRICT_INFER'
_strict_infer_override = []   # stack of bools from strict_infer_shape()


def strict_infer_enabled():
    if _strict_infer_override:
        return _strict_infer_override[-1]
    return os.environ.get(ENV_STRICT_INFER, '').lower() in (
        '1', 'on', 'true', 'yes')


@contextlib.contextmanager
def strict_infer_shape(enable=True):
    """Within this context, append_op(infer_shape=True) failures raise
    lowering.InferShapeError (op type + provenance) instead of silently
    leaving shapes undeclared."""
    _strict_infer_override.append(bool(enable))
    try:
        yield
    finally:
        _strict_infer_override.pop()


class Variable(object):
    """A named tensor in a Block. Reference framework.py:142.

    Holds static metadata only (shape may contain -1 for the batch dim);
    values live in a Scope as jax arrays at run time.
    """

    def __init__(self,
                 block,
                 name=None,
                 shape=None,
                 dtype='float32',
                 lod_level=0,
                 persistable=False,
                 stop_gradient=False,
                 is_data=False,
                 type=None,
                 initializer=None,
                 sharding=None,
                 tiered=False,
                 **kwargs):
        self.block = block
        if name is None:
            name = unique_name.generate('_generated_var')
        self.name = name
        # GSPMD sharding annotation (docs/parallel.md): per-dim mesh-axis
        # names interpreted against the Program's mesh spec (set_mesh).
        # Static metadata like shape/dtype — the Executor turns it into a
        # NamedSharding at lowering time; fluid.analysis.sharding checks
        # consistency ahead of that. Annotated vars capture the layer
        # call that declared the spec (params have no producer op in the
        # main program, so op provenance can't name it).
        self.sharding = normalize_sharding(sharding)
        self._annot_callsite = (_capture_callsite()
                                if self.sharding is not None else None)
        # backed by a host-RAM tier store (embedding.TieredVocabTable
        # stamps this): spills gather WHOLE rows, so the static sharding
        # pass refuses an embedding-dim sharding on a tiered table
        # (DimSharding) the way tiers.validate_program would at runtime
        self.tiered = bool(tiered)
        self.shape = tuple(int(d) for d in shape) if shape is not None else None
        if self.shape is not None and DYN_DIM in self.shape:
            raise ValueError(
                "dim %d collides with the build-time dynamic-batch sentinel "
                "(framework.DYN_DIM); use a different size" % DYN_DIM)
        self.dtype = core.convert_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type or 'LOD_TENSOR'
        self.op = None  # producer op (set by append_op)
        if name not in block.vars:
            block.vars[name] = self

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s, lod=%d)" % (
            self.name, self.shape, self.dtype, self.lod_level)

    __str__ = __repr__

    def to_string(self, throw_on_error=False, with_details=False):
        return repr(self)

    @property
    def ndim(self):
        return len(self.shape)

    def astype(self, dtype):
        from .layers import tensor
        return tensor.cast(self, dtype)

    def _spec(self, batch=DYN_DIM):
        """jax.ShapeDtypeStruct view with -1 dims replaced by `batch`."""
        import jax
        shape = tuple(batch if d == -1 else d for d in self.shape)
        dt = self.dtype
        return jax.ShapeDtypeStruct(shape, np.dtype(dt) if dt != 'bfloat16' else 'bfloat16')

    def _to_dict(self):
        d = dict(name=self.name,
                 shape=list(self.shape) if self.shape is not None else None,
                 dtype=self.dtype, lod_level=self.lod_level,
                 persistable=self.persistable, stop_gradient=self.stop_gradient,
                 is_data=self.is_data, type=self.type,
                 cls=type(self).__name__)
        if self.sharding is not None:
            # only when annotated: un-annotated programs serialize
            # byte-identically to pre-sharding artifacts
            d['sharding'] = _sharding_to_jsonable(self.sharding)
        if self.tiered:
            # same only-when-set policy: the tier mark survives clone()
            # and the artifact round-trip so program_lint --mesh can
            # refuse a dim-sharded tiered table statically
            d['tiered'] = True
        return d


class Parameter(Variable):
    """A persistable, trainable Variable. Reference framework.py:1874."""

    def __init__(self, block, shape, dtype, **kwargs):
        kwargs['persistable'] = True
        self.trainable = kwargs.pop('trainable', True)
        self.optimize_attr = kwargs.pop('optimize_attr', {'learning_rate': 1.0})
        self.regularizer = kwargs.pop('regularizer', None)
        self.gradient_clip_attr = kwargs.pop('gradient_clip_attr', None)
        self.do_model_average = kwargs.pop('do_model_average', None)
        super(Parameter, self).__init__(block, shape=shape, dtype=dtype, **kwargs)

    def _to_dict(self):
        d = super(Parameter, self)._to_dict()
        d['trainable'] = self.trainable
        d['optimize_attr'] = self.optimize_attr
        return d


class Operator(object):
    """One op in a Block. Reference framework.py:431.

    inputs/outputs map slot name -> list of Variable. attrs are plain
    JSON-able python values. The op's semantics are defined solely by the
    lowering rule registered for `type` in ops_impl/.
    """

    # default sentinel: capture the callsite. Callers that already KNOW the
    # op's provenance (clone, _from_dict) pass the preserved value instead
    # — a thousand-op artifact load must not pay a thousand stack walks
    # for values it would immediately overwrite.
    _CAPTURE = object()

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None,
                 callsite=_CAPTURE):
        self.block = block
        self.type = type
        self.inputs = {}
        self.outputs = {}
        # user-code file:line that built this op (None when provenance is
        # disabled); clone()/prune()/_from_dict carry the original through
        # the callsite kwarg, so findings keep pointing at the layer call
        self.callsite = (_capture_callsite()
                         if callsite is Operator._CAPTURE else callsite)
        self.attrs = dict(attrs or {})
        self.attrs.setdefault('op_role', ROLE_FORWARD)
        if _device_guard_stack and _device_guard_stack[-1] is not None:
            self.attrs.setdefault('op_device', _device_guard_stack[-1])
        if inputs:
            for slot, vs in inputs.items():
                if vs is None:
                    continue
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                self.inputs[slot] = list(vs)
        if outputs:
            for slot, vs in outputs.items():
                if vs is None:
                    continue
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                self.outputs[slot] = list(vs)
                for v in vs:
                    if isinstance(v, Variable):
                        v.op = self

    def input(self, slot):
        return [v.name for v in self.inputs.get(slot, [])]

    def output(self, slot):
        return [v.name for v in self.outputs.get(slot, [])]

    @property
    def input_arg_names(self):
        return [v.name for vs in self.inputs.values() for v in vs]

    @property
    def output_arg_names(self):
        return [v.name for vs in self.outputs.values() for v in vs]

    def attr(self, name):
        return self.attrs[name]

    def has_attr(self, name):
        return name in self.attrs

    def _set_attr(self, name, val):
        self.attrs[name] = val
        self.block.program._bump_version()

    set_attr = _set_attr

    def all_attrs(self):
        return dict(self.attrs)

    def __repr__(self):
        ins = {k: [v.name for v in vs] for k, vs in self.inputs.items()}
        outs = {k: [v.name for v in vs] for k, vs in self.outputs.items()}
        return "{%s: %s -> %s %s}" % (self.type, ins, outs,
                                      {k: v for k, v in self.attrs.items()
                                       if k not in ('op_role',)})

    def _to_dict(self):
        d = dict(
            type=self.type,
            inputs={k: [v.name for v in vs] for k, vs in self.inputs.items()},
            outputs={k: [v.name for v in vs] for k, vs in self.outputs.items()},
            attrs={k: v for k, v in self.attrs.items()},
        )
        if self.callsite:
            # provenance survives save/load so program_lint findings on a
            # saved artifact still name the original layer call — but as
            # basename:line, not the absolute build-machine path: an
            # artifact must not leak local filesystem layout, and two
            # checkouts of the same tree must serialize byte-identically
            path, _, line = self.callsite.rpartition(':')
            d['callsite'] = '%s:%s' % (os.path.basename(path), line)
        return d


class Block(object):
    """An ordered op list + var table. Reference framework.py:855."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = collections.OrderedDict()
        self.ops = []

    @property
    def parent_block(self):
        if self.parent_idx < 0:
            return None
        return self.program.block(self.parent_idx)

    def var(self, name):
        v = self.vars.get(name)
        if v is None:
            raise ValueError("Variable %r not found in block %d" % (name, self.idx))
        return v

    def _var_recursive(self, name):
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise ValueError("Variable %r not found (recursive)" % name)

    def has_var(self, name):
        return name in self.vars

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def create_var(self, *args, **kwargs):
        return Variable(self, *args, **kwargs)

    def create_variable(self, *args, **kwargs):
        return Variable(self, *args, **kwargs)

    def create_parameter(self, *args, **kwargs):
        return Parameter(self, *args, **kwargs)

    def append_op(self, type=None, inputs=None, outputs=None, attrs=None,
                  infer_shape=True, callsite=Operator._CAPTURE):
        op = Operator(self, type=type, inputs=inputs, outputs=outputs,
                      attrs=attrs, callsite=callsite)
        self.ops.append(op)
        self.program._bump_version()
        if infer_shape:
            try:
                from . import lowering
                lowering.infer_op_shapes(op, strict=strict_infer_enabled())
            except lowering.NoRuleError:
                pass
        return op

    def _insert_op(self, index, **kwargs):
        op = Operator(self, **kwargs)
        self.ops.insert(index, op)
        self.program._bump_version()
        return op

    def _remove_op(self, index):
        del self.ops[index]
        self.program._bump_version()

    def _to_dict(self):
        return dict(idx=self.idx, parent_idx=self.parent_idx,
                    vars=[v._to_dict() for v in self.vars.values()],
                    ops=[op._to_dict() for op in self.ops])


class Program(object):
    """A list of Blocks; the unit the Executor lowers and jits.

    Reference framework.py:1339. `_version` is a mutation counter used as the
    jit-cache fingerprint (any append/mutation invalidates compiled code).
    """

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._version = 0
        self._seed_counter = 0
        # GSPMD mesh spec (docs/parallel.md): ((axis, size), ...) in mesh
        # layout order + the axis feeds shard their batch dim over. Set by
        # set_mesh(); consumed by the Executor's annotated-sharding path
        # and by fluid.analysis.sharding.
        self._mesh_axes = None
        self._mesh_data_axis = None
        # id(program) can be recycled after GC, colliding in the Executor's
        # jit cache; a monotonically unique uid cannot.
        self._uid = Program._next_uid
        Program._next_uid += 1

    _next_uid = 0

    def set_mesh(self, axes, data_axis=None):
        """Declare the device mesh this Program's sharding annotations
        refer to — the program-level half of the annotation surface
        (docs/parallel.md; the per-tensor half is
        ``ParamAttr(sharding=...)`` / ``Variable(sharding=...)``).

        axes: {'dp': 8} / {'dp': 2, 'model': 4}-style dict (insertion
        order = mesh layout, row-major over the visible devices) or an
        ``((name, size), ...)`` sequence. ``set_mesh(None)`` clears the
        spec. data_axis: the mesh axis feed batches shard their leading
        dim over; defaults to ``'dp'`` (then ``'data'``) when present,
        else feeds replicate. ``data_axis=False`` forces feeds to
        REPLICATE even when a 'dp'/'data' axis exists — the sharded
        SERVING posture (docs/serving.md#pod): request batches are
        bucket-sized, not divisible-by-mesh-sized, while the params
        (e.g. a row-sharded table) stay sharded over the axis.

        The Executor lowers an annotated Program through ONE jitted step
        with explicit in/out shardings and a donation vector over the
        sharded persistables — no strategy wrapper involved; plain
        ``run``/``run_bundle``/``Trainer`` all take this path."""
        # any spec change invalidates the Executor's cached Mesh build
        for a in ('_dist_mesh', '_annot_axes'):
            if hasattr(self, a):
                delattr(self, a)
        if axes is None:
            self._mesh_axes = None
            self._mesh_data_axis = None
            self._bump_version()
            return self
        items = tuple(axes.items()) if isinstance(axes, dict) \
            else tuple((str(n), int(s)) for n, s in axes)
        if not items:
            raise ValueError('set_mesh needs at least one (axis, size)')
        seen = set()
        for name, size in items:
            if not isinstance(name, str) or not name:
                raise ValueError('mesh axis name must be a non-empty '
                                 'string, got %r' % (name,))
            if name in seen:
                raise ValueError('duplicate mesh axis %r' % name)
            seen.add(name)
            if int(size) < 1:
                raise ValueError('mesh axis %r has size %r' % (name, size))
        items = tuple((n, int(s)) for n, s in items)
        if data_axis is False:
            # forced replicate (serving posture): kept as False — NOT
            # collapsed to None — so the choice survives clone() and
            # the _to_dict/_from_dict round-trip (None would re-derive
            # 'dp' on reload and silently re-shard request batches)
            pass
        elif data_axis is None:
            for cand in ('dp', 'data'):
                if cand in seen:
                    data_axis = cand
                    break
        elif data_axis not in seen:
            raise ValueError('data_axis %r is not a mesh axis (have %r)'
                             % (data_axis, sorted(seen)))
        self._mesh_axes = items
        self._mesh_data_axis = data_axis
        self._bump_version()
        return self

    @property
    def mesh_axes(self):
        """The declared mesh spec as an ordered dict, or None."""
        if self._mesh_axes is None:
            return None
        return collections.OrderedDict(self._mesh_axes)

    def _bump_version(self):
        self._version += 1

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def block(self, idx):
        return self.blocks[idx]

    def create_block(self, parent_idx=None):
        new_idx = len(self.blocks)
        parent = self.current_block_idx if parent_idx is None else parent_idx
        self.blocks.append(Block(self, new_idx, parent))
        self.current_block_idx = new_idx
        return self.current_block()

    def rollback(self):
        self.current_block_idx = self.blocks[self.current_block_idx].parent_idx

    @property
    def num_blocks(self):
        return len(self.blocks)

    def list_vars(self):
        for blk in self.blocks:
            for v in blk.vars.values():
                yield v

    def all_parameters(self):
        return self.global_block().all_parameters()

    def clone(self, for_test=False):
        """Deep-copy the program. With for_test=True, prune backward/optimize
        ops and flip is_test on dropout/batch_norm etc. (reference
        Program.clone + inference_optimize)."""
        p = Program()
        p.random_seed = self.random_seed
        # execution flags travel with the program: amp mode (incl. the
        # passes.amp_pass IR-rewrite marker), the Float16Transpiler
        # fetch contract, rematerialisation
        for flag in ('_amp', '_amp_ir', '_fetch_f32', '_use_remat',
                     '_quant', '_quant_ir', '_quant_ops'):
            if hasattr(self, flag):
                setattr(p, flag, getattr(self, flag))
        # the mesh spec travels with the program exactly like _dist_config:
        # a clone of an annotated program stays annotated (per-var specs
        # ride through Variable._to_dict below)
        p._mesh_axes = self._mesh_axes
        p._mesh_data_axis = self._mesh_data_axis
        if getattr(self, '_dist_config', None) is not None:
            # mesh annotations travel with the program (the scope's arrays
            # are already mesh-placed; a meshless clone would mix devices)
            p._dist_config = dict(self._dist_config)
        p.blocks = []
        var_maps = []
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            p.blocks.append(nb)
            vmap = {}
            for v in blk.vars.values():
                d = v._to_dict()
                cls = d.pop('cls')
                d.pop('name')
                if cls == 'Parameter':
                    d.pop('trainable', None)
                    d.pop('optimize_attr', None)
                    nv = Parameter(nb, name=v.name,
                                   trainable=getattr(v, 'trainable', True),
                                   optimize_attr=dict(v.optimize_attr),
                                   regularizer=v.regularizer,
                                   gradient_clip_attr=v.gradient_clip_attr,
                                   do_model_average=v.do_model_average, **d)
                else:
                    nv = Variable(nb, name=v.name, **d)
                vmap[v.name] = nv
            var_maps.append(vmap)
        for bi, blk in enumerate(self.blocks):
            nb = p.blocks[bi]
            vmap = var_maps[bi]

            def lookup(name, bidx=bi):
                b = p.blocks[bidx]
                while b is not None:
                    if name in b.vars:
                        return b.vars[name]
                    b = b.parent_block
                return var_maps[bi][name]

            for op in blk.ops:
                role = op.attrs.get('op_role', ROLE_FORWARD)
                if for_test and role in (ROLE_BACKWARD, ROLE_OPTIMIZE, ROLE_LRSCHED):
                    continue
                ins = {k: [lookup(v.name) for v in vs] for k, vs in op.inputs.items()}
                outs = {k: [lookup(v.name) for v in vs] for k, vs in op.outputs.items()}
                attrs = copy.deepcopy(op.attrs)
                if for_test and 'is_test' in attrs:
                    attrs['is_test'] = True
                nb.append_op(type=op.type, inputs=ins, outputs=outs,
                             attrs=attrs, infer_shape=False,
                             callsite=op.callsite)
        p.current_block_idx = 0
        self._retranspile_pipeline(p)
        p._bump_version()
        return p

    def _retranspile_pipeline(self, p):
        """Re-derive `_pipeline_config` on a clone/prune result: op indices
        shift when ops are dropped, so the config is re-computed from the
        (copied) device_guard stamps. If the surgery broke the stage
        structure, the stamps stay inert and the region runs sequentially
        (same semantics) on the mesh the _dist_config still describes."""
        cfg = getattr(self, '_pipeline_config', None)
        if cfg is None:
            return
        from .transpiler.pipeline_transpiler import PipelineTranspiler
        try:
            PipelineTranspiler(n_micro=cfg['n_micro'],
                               axis=cfg['axis'],
                               n_virtual=cfg.get('n_virtual', 1)
                               ).transpile(p)
        except ValueError:
            p._pipeline_config = None

    def inference_optimize(self):
        return self.clone(for_test=True)

    def verify(self, level='error', startup=None, feeds=None, fetches=None,
               concurrent=False):
        """Static analysis of this program BEFORE lowering (docs/analysis.md):
        dataflow/def-use, shape/dtype inference, donation safety and
        scope-race checks over every block. Returns the list of
        analysis.Finding objects.

        level: 'error' raises analysis.ProgramVerifyError when any
        error-severity finding exists (warnings are warned); 'warn' warns
        for every finding; 'off' skips analysis and returns [].
        startup/feeds/fetches/concurrent refine the context exactly as
        fluid.analysis.analyze does."""
        if level not in ('off', 'warn', 'error'):
            raise ValueError(
                "verify level must be 'off', 'warn' or 'error', got %r"
                % (level,))
        if level == 'off':
            return []
        from . import analysis
        findings = analysis.analyze(self, startup=startup, feeds=feeds,
                                    fetches=fetches, concurrent=concurrent)
        analysis.report_findings(findings, mode=level,
                                 where='Program.verify')
        return findings

    def optimize(self, level='default', feeds=None, fetches=None):
        """Ahead-of-lowering optimization (docs/passes.md): returns a NEW
        Program rewritten by the fluid.passes pipeline — AMP cast
        insertion, constant folding, CSE, and (when `fetches` is given)
        dead-op elimination. This program is never mutated. The
        PassReport lands on the result as `_opt_report`.

        The Executor applies the same pipeline automatically behind
        PADDLE_TPU_OPT={off,default,aggressive}, once per compiled-step
        cache key; this method is the manual/offline surface (e.g.
        optimizing before save_inference_model)."""
        from . import passes
        p, report = passes.optimize(self, feeds=feeds, fetches=fetches,
                                    level=level)
        if p is self:
            # passes.optimize returns the input itself when nothing can
            # run (level='off', pipeline-transpiled) — the executor wants
            # that aliasing, but THIS method promises a program the
            # caller owns and may mutate
            p = self.clone(for_test=False)
            p._opt_report = report
        return p

    def prune(self, targets):
        """Backward-slice the program to the ops needed to compute
        `targets` (reference Program.prune / C++ framework/prune.cc)."""
        if not isinstance(targets, (list, tuple)):
            targets = [targets]
        needed = {t.name if isinstance(t, Variable) else str(t)
                  for t in targets}
        p = self.clone(for_test=False)
        blk = p.global_block()
        keep = []
        for op in reversed(blk.ops):
            out_names = set(op.output_arg_names)
            if out_names & needed:
                keep.append(op)
                needed |= set(op.input_arg_names)
        keep.reverse()
        blk.ops = keep
        p._pipeline_config = None
        self._retranspile_pipeline(p)
        p._bump_version()
        return p

    def to_string(self, throw_on_error=False, with_details=False):
        lines = []
        for blk in self.blocks:
            lines.append("-- block %d (parent %d) --" % (blk.idx, blk.parent_idx))
            for v in blk.vars.values():
                lines.append("    " + repr(v))
            for op in blk.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = to_string
    __repr__ = to_string

    # -- serialization (reference: ProgramDesc protobuf round-trip) --
    def _to_dict(self):
        d = dict(random_seed=self.random_seed,
                 blocks=[b._to_dict() for b in self.blocks])
        if self._mesh_axes is not None:
            # mesh spec survives save/load so program_lint --mesh and a
            # re-loaded artifact see the same annotation context
            d['mesh'] = {'axes': [[n, s] for n, s in self._mesh_axes],
                         'data_axis': self._mesh_data_axis}
        return d

    @staticmethod
    def _from_dict(d):
        p = Program()
        p.random_seed = d.get('random_seed', 0)
        mesh = d.get('mesh')
        if mesh:
            p.set_mesh([(n, s) for n, s in mesh['axes']],
                       data_axis=mesh.get('data_axis'))
        p.blocks = []
        for bd in d['blocks']:
            blk = Block(p, bd['idx'], bd['parent_idx'])
            p.blocks.append(blk)
            for vd in bd['vars']:
                vd = dict(vd)
                cls = vd.pop('cls', 'Variable')
                name = vd.pop('name')
                if cls == 'Parameter':
                    vd.pop('optimize_attr', None)
                    Parameter(blk, name=name, **vd)
                else:
                    Variable(blk, name=name, **vd)
        for bd in d['blocks']:
            blk = p.blocks[bd['idx']]
            for od in bd['ops']:
                ins = {k: [blk._var_recursive(n) for n in vs]
                       for k, vs in od['inputs'].items()}
                outs = {k: [blk._var_recursive(n) for n in vs]
                        for k, vs in od['outputs'].items()}
                # the serialized build site (or None) — never the
                # deserialization frame, which would mislabel every finding
                blk.append_op(type=od['type'], inputs=ins, outputs=outs,
                              attrs=od['attrs'], infer_shape=False,
                              callsite=od.get('callsite'))
        p._bump_version()
        return p


_main_program_ = Program()
_startup_program_ = Program()


def default_startup_program():
    return _startup_program_


def default_main_program():
    return _main_program_


def switch_main_program(program):
    global _main_program_
    prev = _main_program_
    _main_program_ = program
    return prev


def switch_startup_program(program):
    global _startup_program_
    prev = _startup_program_
    _startup_program_ = program
    return prev


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    prev_main = switch_main_program(main_program)
    prev_start = None
    if startup_program is not None:
        prev_start = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_start is not None:
            switch_startup_program(prev_start)


_name_scope_stack = []


@contextlib.contextmanager
def name_scope(prefix=None):
    _name_scope_stack.append(prefix or '')
    try:
        yield
    finally:
        _name_scope_stack.pop()


_device_guard_stack = []


@contextlib.contextmanager
def device_guard(device=None):
    """Op placement annotation (later-Paddle `fluid.device_guard`; the
    closest v0.14 notion is per-op Place dispatch). On TPU, XLA owns chip
    placement, so the only consumed form is 'pipe:K': ops appended inside
    are stamped with pipeline stage K, which PipelineTranspiler turns into
    a GPipe schedule over the `pp` mesh axis (parallel/pipeline.py). Other
    device strings are recorded on the op but ignored."""
    _device_guard_stack.append(device)
    try:
        yield
    finally:
        _device_guard_stack.pop()


def get_var(name, program=None):
    if program is None:
        program = default_main_program()
    return program.global_block()._var_recursive(name)
