"""Weight-decay regularizers.

Parity: reference python/paddle/fluid/regularizer.py — appends
grad-augmentation ops before the optimizer update ops.
"""
from . import framework

__all__ = ['L1Decay', 'L2Decay', 'L1DecayRegularizer', 'L2DecayRegularizer',
           'append_regularization_ops']


class WeightDecayRegularizer(object):
    def __call__(self, param, grad, block):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type='scale', inputs={'X': param}, outputs={'Out': decay},
                        attrs={'scale': self._coeff,
                               'op_role': framework.ROLE_BACKWARD})
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def __call__(self, param, grad, block):
        sign = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type='sign', inputs={'X': param}, outputs={'Out': sign},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        decay = block.create_var(dtype=param.dtype, shape=param.shape)
        block.append_op(type='scale', inputs={'X': sign}, outputs={'Out': decay},
                        attrs={'scale': self._coeff,
                               'op_role': framework.ROLE_BACKWARD})
        return decay


def append_regularization_ops(parameters_and_grads, regularization=None):
    """reference regularizer.py:append_regularization_ops."""
    params_and_grads = []
    for param, grad in parameters_and_grads:
        if grad is None:
            params_and_grads.append((param, grad))
            continue
        regularization_term = None
        if param.regularizer is not None:
            regularization_term = param.regularizer(param, grad, grad.block)
        elif regularization is not None:
            regularization_term = regularization(param, grad, grad.block)
        if regularization_term is None:
            params_and_grads.append((param, grad))
            continue
        block = grad.block
        new_grad = block.create_var(dtype=param.dtype, shape=param.shape,
                                    name=grad.name + '@REG')
        block.append_op(type='elementwise_add',
                        inputs={'X': grad, 'Y': regularization_term},
                        outputs={'Out': new_grad},
                        attrs={'op_role': framework.ROLE_BACKWARD})
        params_and_grads.append((param, new_grad))
    return params_and_grads


# short aliases, reference regularizer.py end-of-module
L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
