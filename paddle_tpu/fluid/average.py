"""WeightedAverage. Parity: reference python/paddle/fluid/average.py."""
import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_(var):
    return isinstance(var, int) or isinstance(var, float) or \
        (isinstance(var, np.ndarray) and var.shape == (1,))


def _is_number_or_matrix_(var):
    return _is_number_(var) or isinstance(var, np.ndarray)


class WeightedAverage(object):
    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix_(value):
            raise ValueError("add(): value must be a number or numpy array")
        if not _is_number_(weight):
            raise ValueError("add(): weight must be a number")
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError("eval() before any add()")
        return self.numerator / self.denominator
