"""DataFeeder: minibatch rows -> feed dict.

Parity: reference python/paddle/fluid/data_feeder.py. Sequence slots
(lod_level>0) are converted to dense-padded SeqValues with power-of-two
length bucketing so XLA sees few distinct shapes (the reference feeds
flattened LoDTensors; padding+bucketing is the TPU-native equivalent).
"""
import numpy as np

from .framework import Variable, default_main_program
from .lod_tensor import LoDTensor

__all__ = ['DataFeeder']


def _bucket(n, minimum=8):
    b = minimum
    while b < n:
        b *= 2
    return b


class DataToLoDTensorConverter(object):
    def __init__(self, place, lod_level, shape, dtype):
        self.place = place
        self.lod_level = lod_level
        self.shape = shape
        self.dtype = dtype
        self.data = []
        self.lod = [[] for _ in range(lod_level)]

    def feed(self, data):
        self._feed_impl_(data, self.lod, self.lod_level)

    def _feed_impl_(self, data, lod, lod_level):
        if lod_level == 0:
            self.data.append(data)
        else:
            lod[0].append(len(data))
            for each_data in data:
                self._feed_impl_(each_data, lod[1:], lod_level - 1)

    def done(self, pad_bucketing=True):
        if self.lod_level == 0:
            arr = np.array(self.data, dtype=self.dtype)
            if self.shape and len(arr.shape) != len(self.shape) + 1:
                arr = arr.reshape([-1] + [abs(int(s)) for s in self.shape])
            return arr
        # sequence slot: _feed_impl_ flattened tokens into self.data and
        # recorded per-sample lengths in self.lod; rebuild padded SeqValue
        from .lowering import SeqValue
        import jax.numpy as jnp
        flat = np.asarray(self.data, dtype=self.dtype)
        if flat.ndim == 1:
            flat = flat[:, None]
        lens = np.asarray(self.lod[-1], dtype=np.int32)
        outer = (jnp.asarray(np.asarray(self.lod[0], np.int32))
                 if self.lod_level > 1 else None)
        offsets = np.concatenate([[0], np.cumsum(lens)])
        seqs = [flat[offsets[i]:offsets[i + 1]] for i in range(len(lens))]
        maxlen = int(lens.max()) if len(lens) else 1
        if pad_bucketing:
            maxlen = _bucket(maxlen)
        trail = seqs[0].shape[1:]
        padded = np.zeros((len(seqs), maxlen) + trail, dtype=self.dtype)
        for i, s in enumerate(seqs):
            padded[i, :s.shape[0]] = s
        return SeqValue(jnp.asarray(padded), jnp.asarray(lens), outer)


class DataFeeder(object):
    """reference data_feeder.py:DataFeeder."""

    def __init__(self, feed_list, place, program=None):
        self.feed_dtypes = []
        self.feed_names = []
        self.feed_shapes = []
        self.feed_lod_level = []
        if program is None:
            program = default_main_program()
        for each_var in feed_list:
            if isinstance(each_var, str):
                each_var = program.global_block().var(each_var)
            if not isinstance(each_var, Variable):
                raise TypeError("Feed list should contain Variables or names")
            self.feed_dtypes.append(each_var.dtype)
            self.feed_names.append(each_var.name)
            self.feed_lod_level.append(each_var.lod_level)
            shape = each_var.shape
            self.feed_shapes.append([d for d in shape if d != -1] if shape else None)
        self.place = place

    def feed(self, iterable):
        converters = [
            DataToLoDTensorConverter(self.place, lod_level=lod, shape=shape,
                                     dtype=dtype)
            for lod, shape, dtype in zip(self.feed_lod_level,
                                         self.feed_shapes, self.feed_dtypes)]
        for each_sample in iterable:
            assert len(each_sample) == len(converters), (
                "feed sample has %d slots, expected %d" %
                (len(each_sample), len(converters)))
            for each_converter, each_slot in zip(converters, each_sample):
                each_converter.feed(each_slot)
        return {name: conv.done()
                for name, conv in zip(self.feed_names, converters)}

    def feed_parallel(self, iterable, num_places=None):
        """Split a batch across mesh shards (used with ParallelExecutor);
        on GSPMD the full batch is fed once and sharded by the mesh, so this
        just feeds the concatenation."""
        rows = []
        for it in iterable:
            rows.extend(it)
        return self.feed(rows)
