"""Quantization op rules: explicit int8 <-> float boundaries in the IR.

Parity: the reference grew fake_quantize/fake_dequantize operators
(paddle/fluid/operators/fake_quantize_op.*) for its slim/quant-aware
tooling — scales computed per tensor or per channel, int8 storage for
inference. Here the same boundaries are three PURE rules the quant pass
(fluid/passes/quant_pass.py) inserts, so `analysis`, provenance and
`program_lint` see every precision change as a real op — the same
visibility argument as the AMP IR rewrite — and constant folding can
evaluate a `quantize` of a frozen weight at optimization time through
the rule itself (one definition of the rounding semantics).

Scheme (docs/perf.md#quantized-inference carries the tolerance table):
symmetric linear int8, per-channel absmax scales — `scale[ch] =
max|x[ch]| / 127` (floored so all-zero channels stay finite), `q =
clip(round(x / scale), -127, 127)`. Scales keep their reduced axes
(`[V, 1]` for a row-quantized table), so dequantize is a plain
broadcast multiply and the scales ship as ordinary persistables.

All three rules are deterministic, context-free functions of their
inputs — foldable by fluid.passes (is_foldable) by construction.
"""
import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like

# absmax floor: keeps all-zero channels' scales finite; round-trips of
# genuinely zero rows stay exactly zero because q is 0 there anyway
SCALE_FLOOR = 1e-12
QMAX = 127.0


def quantize_array(x, axis=0):
    """(q int8, scale f32 keepdims) for per-channel symmetric absmax
    quantization along `axis`. Shared by the lowering rule, the offline
    weight quantizer (passes.quant_pass.quantize_weights) and the
    embedding row store (embedding.quant_rows) — ONE definition of the
    rounding semantics."""
    x = jnp.asarray(x, jnp.float32)
    axes = tuple(a for a in range(x.ndim) if a != axis % max(x.ndim, 1))
    amax = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    scale = jnp.maximum(amax / QMAX, SCALE_FLOOR)
    q = jnp.clip(jnp.round(x / scale), -QMAX, QMAX).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


@register('quantize')
def _quantize(ins, attrs, ctx):
    q, scale = quantize_array(data_of(ins['X'][0]),
                              axis=int(attrs.get('axis', 0)))
    return {'Out': q, 'Scale': scale}


@register('dequantize')
def _dequantize(ins, attrs, ctx):
    q = data_of(ins['X'][0])
    scale = data_of(ins['Scale'][0])
    return {'Out': q.astype(jnp.float32) * scale}


@register('quant_lookup_table')
def _quant_lookup_table(ins, attrs, ctx):
    """lookup_table over an int8 row-quantized table: gather the int8
    rows AND their [V, 1] scales by id, dequantize AFTER the gather — the
    fp32 [V, D] table never materializes, so serving HBM for the
    embedding is the int8 bytes + one f32 scale per row (the vocab-per-
    HBM-byte doubling docs/perf.md claims). Semantics match
    sequence_ops._lookup_table_dense exactly: dequant-then-gather and
    gather-then-dequant are the same elementwise math, and padding_idx
    zeroes the row via its SCALE (0 * q == 0.0, the dense rule's
    `w.at[pad].set(0)`)."""
    w = data_of(ins['W'][0])                         # int8 [V, D]
    scale = data_of(ins['Scale'][0])                 # f32 [V, 1]
    ids_v = ins['Ids'][0]
    ids = data_of(ids_v).astype(jnp.int32)
    if ids.shape and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    if attrs.get('padding_idx') is not None and attrs['padding_idx'] >= 0:
        scale = scale.at[attrs['padding_idx']].set(0.0)
    rows = jnp.take(w, ids, axis=0).astype(jnp.float32)
    row_scale = jnp.take(scale, ids, axis=0)
    # scale keepdims [V, 1] gathers to [..., 1]: broadcasts over the
    # embedding dim whatever the id rank
    out = rows * row_scale
    from .lod_beam import is_beam_form
    if is_beam_form(ids_v) and out.ndim == ids.ndim + 1:
        out = out[:, None]
    return {'Out': like(ids_v, out)}
