"""Control-flow & debug rules.

Parity: reference paddle/fluid/operators/{while,conditional_block,print,...}_op.cc.
Structured control flow (While/IfElse/StaticRNN) is handled by the layers in
layers/control_flow.py which lower their sub-blocks through lax.while_loop /
lax.cond / lax.scan; the ops here are the leaf primitives.
"""
import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like


@register('print')
def _print(ins, attrs, ctx):
    x = ins['In'][0]
    msg = attrs.get('message', '')
    jax.debug.print(msg + " {x}", x=data_of(x))
    return {'Out': x}


@register('isfinite')
def _isfinite(ins, attrs, ctx):
    xs = [data_of(v) for v in ins['X']]
    ok = jnp.asarray(True)
    for x in xs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(x)))
    return {'Out': ok}
