"""Embedding + variable-length sequence rules.

Parity: reference paddle/fluid/operators/{lookup_table,sequence_pool,
sequence_softmax,sequence_expand,sequence_conv,sequence_reshape,
sequence_mask,lod_reset,row_conv,lstm,gru,...}_op.*

TPU-first: the reference stores sequences flattened [total_tokens, d] with a
LoD offset table and walks it with per-sequence CPU loops / custom CUDA
kernels. Here sequences are dense-padded [batch, max_len, d] SeqValues with
an int32 lengths vector; every rule is a masked dense op (static shapes for
XLA) and recurrences are lax.scan over the time axis — the XLA-native RNN.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import register, data_of, like, SeqValue


@register('lookup_table')
def _lookup_table(ins, attrs, ctx):
    from . import embedding_ops
    if embedding_ops.dist_lookup_applies(attrs, ctx):
        # row-sharded table on a mesh: the all_to_all lookup wire
        # (docs/embedding.md) replaces the dense gather
        return embedding_ops.lookup_table_dist(ins, attrs, ctx)
    return _lookup_table_dense(ins, attrs, ctx)


def _lookup_table_dense(ins, attrs, ctx):
    """The dense gather (no dispatch) — also the distributed rule's
    fallback when the vocab cannot tile over the mesh axis."""
    w = data_of(ins['W'][0])
    ids_v = ins['Ids'][0]
    ids = data_of(ids_v).astype(jnp.int32)
    if ids.shape and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    if attrs.get('padding_idx') is not None and attrs['padding_idx'] >= 0:
        pad = attrs['padding_idx']
        w = w.at[pad].set(0.0)
    out = jnp.take(w, ids, axis=0)
    from .lod_beam import is_beam_form
    if is_beam_form(ids_v) and out.ndim == ids.ndim + 1:
        # capacity-form beam rows [R] embed to [R, 1, E]: each row is a
        # one-token level-1 group and downstream fc ops were
        # shape-inferred for the padded 3-D layout (decode idiom)
        out = out[:, None]
    return {'Out': like(ids_v, out)}


def _seq(v):
    if not isinstance(v, SeqValue):
        raise TypeError("expected a sequence (lod) value, got dense array")
    return v


@register('sequence_pool')
def _sequence_pool(ins, attrs, ctx):
    x = _seq(ins['X'][0])
    ptype = attrs.get('pooltype', 'AVERAGE').upper()
    data = x.data  # [B, T, ...]
    mask = x.mask(data.dtype)
    while mask.ndim < data.ndim:
        mask = mask[..., None]
    lens = jnp.maximum(x.lengths, 1).astype(data.dtype)
    lens = lens.reshape((-1,) + (1,) * (data.ndim - 2))
    if ptype == 'SUM':
        out = jnp.sum(data * mask, axis=1)
    elif ptype == 'AVERAGE':
        out = jnp.sum(data * mask, axis=1) / lens
    elif ptype == 'SQRT':
        out = jnp.sum(data * mask, axis=1) / jnp.sqrt(lens)
    elif ptype == 'MAX':
        neg = jnp.finfo(data.dtype).min if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        out = jnp.max(jnp.where(mask > 0, data, neg), axis=1)
    elif ptype == 'FIRST':
        out = data[:, 0]
    elif ptype == 'LAST':
        idx = jnp.maximum(x.lengths - 1, 0)
        out = jnp.take_along_axis(
            data, idx.reshape((-1, 1) + (1,) * (data.ndim - 2)), axis=1)
        out = jnp.squeeze(out, 1)
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    if x.outer_lengths:
        # Nested LoD: pooling consumes the innermost level only (reference
        # sequence_pool_op pools the last LoD level); the pooled rows — one
        # per inner sequence — regroup under the next level out, which
        # becomes the new innermost.
        out = _regroup_rows(out, x.outer_lengths[-1],
                            x.outer_lengths[:-1] or None)
    return {'Out': out, 'MaxIndex': None}


def _regroup_rows(rows, group_lens, remaining_outers):
    """[B, ...] rows -> padded SeqValue [G, B, ...] grouped into runs of
    group_lens (int32[G]) consecutive rows. The time axis is padded to the
    static bound B (total rows) so shapes stay static under jit."""
    b = rows.shape[0]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(group_lens.astype(jnp.int32))[:-1]])
    j = jnp.arange(b, dtype=jnp.int32)
    idx = jnp.clip(starts[:, None] + j[None, :], 0, b - 1)  # [G, B]
    valid = j[None, :] < group_lens[:, None]
    out = rows[idx]                                          # [G, B, ...]
    while valid.ndim < out.ndim:
        valid = valid[..., None]
    out = jnp.where(valid, out, jnp.zeros((), out.dtype))
    return SeqValue(out, group_lens, remaining_outers)


@register('sequence_softmax')
def _sequence_softmax(ins, attrs, ctx):
    x = _seq(ins['X'][0])
    data = x.data
    m = x.mask(jnp.float32)
    while m.ndim < data.ndim:
        m = m[..., None]
    neg = jnp.finfo(jnp.float32).min
    logits = jnp.where(m > 0, data.astype(jnp.float32), neg)
    sm = jax.nn.softmax(logits, axis=1) * m
    return {'Out': SeqValue(sm.astype(data.dtype), x.lengths, x.outer_lengths)}


@register('sequence_expand')
def _sequence_expand(ins, attrs, ctx):
    """Broadcast per-row x over y's time steps (reference
    operators/sequence_expand_op.cc, ref_level=-1 common case)."""
    xv = ins['X'][0]
    from .lod_beam import is_beam_form, sequence_expand_beam
    if is_beam_form(ins['Y'][0]):
        # the book's LoD beam decoder: replicate each parent state over
        # its selected children (capacity form, lod_beam.py)
        return {'Out': sequence_expand_beam(xv, ins['Y'][0])}
    y = _seq(ins['Y'][0])
    x = data_of(xv)
    t = y.data.shape[1]
    if isinstance(xv, SeqValue):
        # expand whole sub-sequences: x [B, Tx, ...] tiled is not
        # representable densely without ragged repeat; common usage in the
        # book models is row-expand, so take first step per row.
        x = x[:, 0]
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    return {'Out': SeqValue(out, y.lengths, y.outer_lengths)}


@register('sequence_reshape')
def _sequence_reshape(ins, attrs, ctx):
    x = _seq(ins['X'][0])
    new_dim = attrs['new_dim']
    b, t, d = x.data.shape
    assert (t * d) % new_dim == 0
    new_t = t * d // new_dim
    out = x.data.reshape(b, new_t, new_dim)
    new_len = (x.lengths * d) // new_dim
    return {'Out': SeqValue(out, new_len)}


@register('sequence_mask')
def _sequence_mask(ins, attrs, ctx):
    lens = data_of(ins['X'][0]).reshape(-1)
    maxlen = attrs.get('maxlen', -1)
    if maxlen is None or maxlen < 0:
        maxlen = int(lens.shape[0]) if False else attrs.get('static_maxlen', None)
        if maxlen is None:
            raise ValueError(
                "sequence_mask on TPU needs a static maxlen attr (dynamic "
                "max length would make the output shape data-dependent)")
    rng = jnp.arange(maxlen)
    from .tensor_ops import _np_dtype
    mask = (rng[None, :] < lens[:, None]).astype(_np_dtype(attrs.get('out_dtype', 'int64')))
    return {'Y': mask}


@register('lod_reset')
def _lod_reset(ins, attrs, ctx):
    """Reinterpret the token buffer under a new LoD (reference
    operators/lod_reset_op.cc works on the flat buffer + offsets).

    With a static target_lod whose sequence count differs from the input
    batch, the padded-dense layout is genuinely regrouped: valid tokens
    are flattened and re-padded to [n_seqs, max_new_len, ...]. With a
    dynamic Y length source the batch dim must stay (static shapes), so
    only the per-row lengths are replaced."""
    xv = ins['X'][0]
    data = data_of(xv)
    if ins.get('Y') and ins['Y']:
        y = ins['Y'][0]
        from .lod_beam import is_beam_form
        if is_beam_form(y):
            # beam decode idiom: adopt Y's full 2-level capacity LoD
            # (including its beam flag — the output IS capacity form)
            return {'Out': SeqValue(data, y.lengths, y.outer_lengths,
                                    beam_cap=True)}
        lens = y.lengths if isinstance(y, SeqValue) else data_of(y).reshape(-1).astype(jnp.int32)
        if lens.shape[0] != data.shape[0]:
            raise ValueError(
                'lod_reset with a dynamic Y length source cannot regroup '
                'the batch (%d rows -> %d sequences needs static lengths; '
                'pass target_lod instead)' % (data.shape[0], lens.shape[0]))
        return {'Out': SeqValue(data, lens)}
    offsets = np.asarray(attrs['target_lod'])
    if offsets.size == 0 or offsets[0] != 0 or (np.diff(offsets) < 0).any():
        raise ValueError(
            'lod_reset: target_lod must be a non-decreasing level-0 '
            'offsets list starting at 0 (reference lod_reset_op.cc), '
            'got %r' % (list(offsets),))
    new_lens = np.diff(offsets)
    lens = jnp.asarray(new_lens, dtype=jnp.int32)
    # Regroup under jit regardless of whether the sequence COUNT changed —
    # the partition may differ even at equal counts. New lengths are
    # static (attr); old ones may be traced, so token j of the flat
    # valid-token stream is fetched with a computed (row, col) gather and
    # re-padded via a static index/mask matrix. If target_lod over-covers
    # the valid tokens, the clamped reads yield repeated edge tokens (the
    # reference errors at runtime; one fused XLA step cannot).
    if isinstance(xv, SeqValue):
        old_lens = xv.lengths.astype(jnp.int32)
        cum = jnp.cumsum(old_lens)
        prev = jnp.concatenate([jnp.zeros((1,), jnp.int32), cum[:-1]])
        n_new = int(new_lens.sum())
        j = jnp.arange(n_new)
        row = jnp.searchsorted(cum, j, side='right').astype(jnp.int32)
        row = jnp.clip(row, 0, data.shape[0] - 1)
        col = jnp.clip(j - prev[row], 0, data.shape[1] - 1)
        flat = data[row, col]                       # [n_new, ...]
    else:
        # dense input: every row IS a token (reference attaches a LoD to
        # a flat [N, d] buffer)
        n_new = int(new_lens.sum())
        flat = data[:n_new]
    maxlen = int(new_lens.max()) if len(new_lens) else 1
    idx = np.zeros((len(new_lens), maxlen), np.int32)
    mask = np.zeros((len(new_lens), maxlen), bool)
    off = 0
    for i, l in enumerate(new_lens):
        idx[i, :int(l)] = np.arange(off, off + int(l))
        mask[i, :int(l)] = True
        off += int(l)
    out = flat[idx]                                 # [B', maxlen, ...]
    m = jnp.asarray(mask).reshape(mask.shape + (1,) * (out.ndim - 2))
    return {'Out': SeqValue(jnp.where(m, out, 0), lens)}


@register('sequence_conv')
def _sequence_conv(ins, attrs, ctx):
    """Context-window projection (reference operators/sequence_conv_op.cc):
    for each step, concat [t+start, t+start+len) rows then matmul filter
    [len*d, out]. Dense: gather shifted copies, mask invalid."""
    x = _seq(ins['X'][0])
    filt = data_of(ins['Filter'][0])
    clen = attrs.get('contextLength', 3)
    cstart = attrs.get('contextStart', -((clen - 1) // 2))
    b, t, d = x.data.shape
    m = x.mask(x.data.dtype)[..., None]
    xm = x.data * m
    cols = []
    for i in range(clen):
        off = cstart + i
        rolled = jnp.roll(xm, -off, axis=1)
        step = jnp.arange(t)
        valid = (step + off >= 0) & (step + off < t)
        cols.append(jnp.where(valid[None, :, None], rolled, 0.0))
    ctxmat = jnp.concatenate(cols, axis=-1)  # [B, T, clen*d]
    out = ctxmat @ filt  # [B, T, out]
    return {'Out': SeqValue(out, x.lengths)}


@register('row_conv')
def _row_conv(ins, attrs, ctx):
    """Lookahead conv (reference operators/row_conv_op.cc): out[t] =
    sum_{i<k} w[i] * x[t+i]."""
    x = _seq(ins['X'][0])
    filt = data_of(ins['Filter'][0])  # [future_ctx, d]
    k = filt.shape[0]
    b, t, d = x.data.shape
    m = x.mask(x.data.dtype)[..., None]
    xm = x.data * m
    out = jnp.zeros_like(xm)
    for i in range(k):
        rolled = jnp.roll(xm, -i, axis=1)
        step = jnp.arange(t)
        valid = (step + i < t)
        out = out + jnp.where(valid[None, :, None], rolled, 0.0) * filt[i][None, None, :]
    return {'Out': SeqValue(out, x.lengths)}


def _lstm_scan(xproj, lengths, w_hid, bias, use_peepholes, cand_act, gate_act,
               cell_act, is_reverse, h0=None, c0=None, proj=None):
    """Shared LSTM recurrence. xproj: [B, T, 4D] (input already projected).
    Gate layout i, f, c, o with hidden weight [D, 4D]
    (reference operators/math/detail/lstm_kernel.h). lax.scan over time."""
    b, t, d4 = xproj.shape
    d = d4 // 4
    acts = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
            'relu': lambda v: jnp.maximum(v, 0), 'identity': lambda v: v}
    ga, ca, cea = acts[gate_act], acts[cand_act], acts[cell_act]
    if h0 is None:
        hdim = proj.shape[1] if proj is not None else d
        h0 = jnp.zeros((b, hdim), xproj.dtype)
    if c0 is None:
        c0 = jnp.zeros((b, d), xproj.dtype)
    if bias is not None:
        gate_bias = bias[..., :d4].reshape(1, d4)
    else:
        gate_bias = 0.0
    if use_peepholes and bias is not None:
        w_ic = bias[..., d4:d4 + d].reshape(1, d)
        w_fc = bias[..., d4 + d:d4 + 2 * d].reshape(1, d)
        w_oc = bias[..., d4 + 2 * d:d4 + 3 * d].reshape(1, d)
    else:
        w_ic = w_fc = w_oc = None

    xs = jnp.swapaxes(xproj, 0, 1)  # [T, B, 4D]
    steps = jnp.arange(t)
    if is_reverse:
        xs = jnp.flip(xs, 0)
        step_ids = jnp.flip(steps, 0)
    else:
        step_ids = steps
    valid_t = (step_ids[:, None] < lengths[None, :])  # [T, B]

    def step(carry, inp):
        h, c = carry
        x_t, valid = inp
        g = x_t + h @ w_hid + gate_bias
        gi, gf, gc, go = jnp.split(g, 4, axis=-1)
        if w_ic is not None:
            gi = gi + w_ic * c
            gf = gf + w_fc * c
        i = ga(gi)
        f = ga(gf)
        cand = ca(gc)
        c_new = f * c + i * cand
        if w_oc is not None:
            go = go + w_oc * c_new
        o = ga(go)
        h_new = o * cea(c_new)
        if proj is not None:
            h_new = h_new @ proj
        vm = valid[:, None].astype(h_new.dtype)
        h_out = vm * h_new + (1 - vm) * h
        c_out = vm * c_new + (1 - vm) * c
        return (h_out, c_out), (h_out, c_out)

    (_, _), (hs, cs) = lax.scan(step, (h0, c0), (xs, valid_t))
    if is_reverse:
        hs = jnp.flip(hs, 0)
        cs = jnp.flip(cs, 0)
    return jnp.swapaxes(hs, 0, 1), jnp.swapaxes(cs, 0, 1)


@register('lstm')
def _lstm(ins, attrs, ctx):
    x = _seq(ins['Input'][0])
    w = data_of(ins['Weight'][0])  # [D, 4D]
    bias = data_of(ins['Bias'][0]) if ins.get('Bias') else None
    h0 = data_of(ins['H0'][0]) if ins.get('H0') else None
    c0 = data_of(ins['C0'][0]) if ins.get('C0') else None
    hs, cs = _lstm_scan(
        x.data, x.lengths, w, bias,
        attrs.get('use_peepholes', True),
        attrs.get('candidate_activation', 'tanh'),
        attrs.get('gate_activation', 'sigmoid'),
        attrs.get('cell_activation', 'tanh'),
        attrs.get('is_reverse', False), h0, c0)
    return {'Hidden': SeqValue(hs, x.lengths), 'Cell': SeqValue(cs, x.lengths),
            'BatchGate': None, 'BatchCellPreAct': None}


@register('lstmp')
def _lstmp(ins, attrs, ctx):
    x = _seq(ins['Input'][0])
    w = data_of(ins['Weight'][0])  # [P, 4D]
    proj = data_of(ins['ProjWeight'][0])  # [D, P]
    bias = data_of(ins['Bias'][0]) if ins.get('Bias') else None
    hs, cs = _lstm_scan(
        x.data, x.lengths, w, bias,
        attrs.get('use_peepholes', True),
        attrs.get('candidate_activation', 'tanh'),
        attrs.get('gate_activation', 'sigmoid'),
        attrs.get('cell_activation', 'tanh'),
        attrs.get('is_reverse', False), None, None, proj=proj)
    return {'Projection': SeqValue(hs, x.lengths), 'Cell': SeqValue(cs, x.lengths),
            'BatchGate': None, 'BatchCellPreAct': None,
            'BatchHidden': None, 'OrderedP0': None}


def _gru_gates(x_t, h_prev, w, gate_act, cand_act):
    """w: [D, 3D] laid out [update, reset | candidate]
    (reference operators/math/detail/gru_kernel.h)."""
    d = h_prev.shape[-1]
    w_rz = w[:, :2 * d]
    w_c = w[:, 2 * d:]
    g_rz = x_t[:, :2 * d] + h_prev @ w_rz
    u = gate_act(g_rz[:, :d])
    r = gate_act(g_rz[:, d:])
    c = cand_act(x_t[:, 2 * d:] + (r * h_prev) @ w_c)
    h_new = u * h_prev + (1 - u) * c
    return h_new, r, u, c


@register('gru')
def _gru(ins, attrs, ctx):
    x = _seq(ins['Input'][0])  # [B, T, 3D]
    w = data_of(ins['Weight'][0])
    bias = data_of(ins['Bias'][0]) if ins.get('Bias') else 0.0
    h0 = data_of(ins['H0'][0]) if ins.get('H0') else None
    acts = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
            'relu': lambda v: jnp.maximum(v, 0), 'identity': lambda v: v}
    ga = acts[attrs.get('gate_activation', 'sigmoid')]
    ca = acts[attrs.get('activation', 'tanh')]
    b, t, d3 = x.data.shape
    d = d3 // 3
    if h0 is None:
        h0 = jnp.zeros((b, d), x.data.dtype)
    xdata = x.data if isinstance(bias, float) else x.data + jnp.reshape(bias, (1, 1, -1))
    xs = jnp.swapaxes(xdata, 0, 1)
    steps = jnp.arange(t)
    if attrs.get('is_reverse', False):
        xs = jnp.flip(xs, 0)
        steps = jnp.flip(steps, 0)
    valid_t = (steps[:, None] < x.lengths[None, :])

    def step(h, inp):
        x_t, valid = inp
        h_new, _, _, _ = _gru_gates(x_t, h, w, ga, ca)
        vm = valid[:, None].astype(h_new.dtype)
        h_out = vm * h_new + (1 - vm) * h
        return h_out, h_out

    _, hs = lax.scan(step, h0, (xs, valid_t))
    if attrs.get('is_reverse', False):
        hs = jnp.flip(hs, 0)
    return {'Hidden': SeqValue(jnp.swapaxes(hs, 0, 1), x.lengths),
            'BatchGate': None, 'BatchResetHiddenPrev': None, 'BatchHidden': None}


@register('gru_unit')
def _gru_unit(ins, attrs, ctx):
    x = data_of(ins['Input'][0])  # [B, 3D]
    h_prev = data_of(ins['HiddenPrev'][0])
    w = data_of(ins['Weight'][0])
    bias = data_of(ins['Bias'][0]).reshape(1, -1) if ins.get('Bias') else 0.0
    acts = {1: jax.nn.sigmoid, 2: jnp.tanh, 0: lambda v: v,
            3: lambda v: jnp.maximum(v, 0)}
    # attr may be int enum (reference) or str
    def act(a, default):
        v = attrs.get(a, default)
        if isinstance(v, str):
            return {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
                    'identity': lambda u: u,
                    'relu': lambda u: jnp.maximum(u, 0)}[v]
        return acts[v]
    ga = act('gate_activation', 'sigmoid')
    ca = act('activation', 'tanh')
    h_new, r, u, c = _gru_gates(x + bias, h_prev, w, ga, ca)
    return {'Hidden': h_new, 'ResetHiddenPrev': r * h_prev, 'Gate': u}


@register('lstm_unit')
def _lstm_unit(ins, attrs, ctx):
    x = data_of(ins['X'][0])  # [B, 4D] pre-projected gates
    c_prev = data_of(ins['C_prev'][0])
    forget_bias = attrs.get('forget_bias', 0.0)
    gi, gf, gc, go = jnp.split(x, 4, axis=-1)
    i = jax.nn.sigmoid(gi)
    f = jax.nn.sigmoid(gf + forget_bias)
    o = jax.nn.sigmoid(go)
    c = f * c_prev + i * jnp.tanh(gc)
    h = o * jnp.tanh(c)
    return {'C': c, 'H': h}


@register('attention_lstm_decoder')
def _attention_lstm_decoder(ins, attrs, ctx):
    """Fused attention decoder for seq2seq (parity with the reference's
    per-step ConditionalBlock/StaticRNN decoder in
    benchmark/fluid/models/machine_translation.py:lstm_step — there the
    attention+cell is re-dispatched op-by-op every timestep; here it is one
    lax.scan whose body is three MXU matmuls).

    Inputs:
      TrgEmb   [B, T, E]   (SeqValue) target-side embeddings (teacher forcing)
      EncOut   [B, S, D]   (SeqValue) encoder outputs
      WDec     [E+D, 4H]   input+context -> gates
      UDec     [H, 4H]     hidden -> gates
      BDec     [1, 4H]
      WAttnQ   [H, D]      decoder-state -> attention query
    Output: Hidden [B, T, H] (SeqValue)
    """
    trg = _seq(ins['TrgEmb'][0])
    enc = _seq(ins['EncOut'][0])
    w_dec = data_of(ins['WDec'][0])
    u_dec = data_of(ins['UDec'][0])
    b_dec = data_of(ins['BDec'][0]) if ins.get('BDec') else 0.0
    w_q = data_of(ins['WAttnQ'][0])
    b, t, e = trg.data.shape
    s = enc.data.shape[1]
    h = u_dec.shape[0]
    enc_mask = enc.mask(jnp.float32)  # [B, S]
    neg = jnp.finfo(jnp.float32).min

    xs = jnp.swapaxes(trg.data, 0, 1)  # [T, B, E]
    steps = jnp.arange(t)
    valid_t = (steps[:, None] < trg.lengths[None, :])  # [T, B]

    h0 = jnp.zeros((b, h), trg.data.dtype)
    c0 = jnp.zeros((b, h), trg.data.dtype)

    def step(carry, inp):
        hp, cp = carry
        x_t, valid = inp
        # dot-product attention over encoder states
        q = hp @ w_q  # [B, D]
        scores = jnp.einsum('bd,bsd->bs', q, enc.data)
        scores = jnp.where(enc_mask > 0, scores, neg)
        alpha = jax.nn.softmax(scores, axis=-1)
        ctx_vec = jnp.einsum('bs,bsd->bd', alpha, enc.data)  # [B, D]
        g = jnp.concatenate([x_t, ctx_vec], axis=-1) @ w_dec + hp @ u_dec + b_dec
        gi, gf, gc, go = jnp.split(g, 4, axis=-1)
        i = jax.nn.sigmoid(gi)
        f = jax.nn.sigmoid(gf)
        o = jax.nn.sigmoid(go)
        c_new = f * cp + i * jnp.tanh(gc)
        h_new = o * jnp.tanh(c_new)
        vm = valid[:, None].astype(h_new.dtype)
        return (vm * h_new + (1 - vm) * hp, vm * c_new + (1 - vm) * cp), \
            vm * h_new
    _, hs = lax.scan(step, (h0, c0), (xs, valid_t))
    return {'Hidden': SeqValue(jnp.swapaxes(hs, 0, 1), trg.lengths)}
