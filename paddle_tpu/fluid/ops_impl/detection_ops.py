"""Detection (SSD family) rules.

Parity: reference paddle/fluid/operators/detection/*. Implemented as masked
dense JAX; the handful that are inherently host-side dynamic (NMS output
lists) return fixed-size padded results with validity counts.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of


@register('prior_box')
def _prior_box(ins, attrs, ctx):
    """reference operators/detection/prior_box_op.cc."""
    feat = data_of(ins['Input'][0])  # NCHW feature map
    img = data_of(ins['Image'][0])
    min_sizes = list(attrs['min_sizes'])
    max_sizes = list(attrs.get('max_sizes', []) or [])
    ars = list(attrs.get('aspect_ratios', [1.0]))
    flip = attrs.get('flip', False)
    variances = list(attrs.get('variances', [0.1, 0.1, 0.2, 0.2]))
    clip = attrs.get('clip', False)
    step_w = attrs.get('step_w', 0.0)
    step_h = attrs.get('step_h', 0.0)
    offset = attrs.get('offset', 0.5)

    full_ars = [1.0]
    for ar in ars:
        if abs(ar - 1.0) < 1e-6:
            continue
        full_ars.append(ar)
        if flip:
            full_ars.append(1.0 / ar)

    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w if step_w > 0 else iw / fw
    sh = step_h if step_h > 0 else ih / fh

    boxes = []
    for ms in min_sizes:
        for ar in full_ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = np.sqrt(ms * mx) / 2.0
            boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [fh, fw]
    out = []
    for bw, bh in boxes:
        b = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], axis=-1)
        out.append(b)
    out = jnp.stack(out, axis=2)  # [fh, fw, np, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           out.shape[:-1] + (4,))
    return {'Boxes': out, 'Variances': var}


@register('box_coder')
def _box_coder(ins, attrs, ctx):
    """reference operators/detection/box_coder_op.cc (decode_center_size)."""
    prior = data_of(ins['PriorBox'][0])  # [M, 4]
    pvar = data_of(ins['PriorBoxVar'][0]) if ins.get('PriorBoxVar') else None
    target = data_of(ins['TargetBox'][0])
    code_type = attrs.get('code_type', 'decode_center_size')
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if pvar is None:
        pvar = jnp.ones_like(prior)
    if 'decode' in code_type:
        # target: [N, M, 4]
        tcx = pvar[..., 0] * target[..., 0] * pw + pcx
        tcy = pvar[..., 1] * target[..., 1] * ph + pcy
        tw = jnp.exp(pvar[..., 2] * target[..., 2]) * pw
        th = jnp.exp(pvar[..., 3] * target[..., 3]) * ph
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2, tcy + th / 2], axis=-1)
    else:
        # encode: target [N, 4] gt boxes vs priors [M, 4] -> [N, M, 4]
        out = _encode_boxes(target[:, None, :], prior[None, :, :],
                            pvar[None, :, :])
    return {'OutputBox': out}


def _iou(a, b):
    """IoU matrix between a [..., N, 4] and b [..., M, 4] -> [..., N, M]."""
    ax1, ay1, ax2, ay2 = (a[..., :, None, i] for i in range(4))
    bx1, by1, bx2, by2 = (b[..., None, :, i] for i in range(4))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = jnp.maximum(ax2 - ax1, 0.0) * jnp.maximum(ay2 - ay1, 0.0)
    area_b = jnp.maximum(bx2 - bx1, 0.0) * jnp.maximum(by2 - by1, 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


_BIG_NEG = -1e9


def _bipartite_greedy(dist):
    """Greedy bipartite matching on dist [N, M] (rows=gt, cols=priors).

    Returns (col_to_row [M] int32, col_dist [M]); -1 where unmatched.
    Reference operators/detection/bipartite_match_op.cc — the sequential
    global-argmax loop becomes a lax.fori_loop of masked argmaxes.
    """
    N, M = dist.shape
    steps = min(N, M)

    def body(_, carry):
        d, col_match, col_dist = carry
        flat = jnp.argmax(d)
        r, c = flat // M, flat % M
        val = d[r, c]
        # reference bipartite_match_op.cc only matches when dist > kEPS
        # (1e-6): a gt box overlapping nothing must stay unmatched, not be
        # assigned to prior 0 as a garbage positive.
        ok = val > 1e-6
        col_match = jnp.where(ok, col_match.at[c].set(r.astype(jnp.int32)),
                              col_match)
        col_dist = jnp.where(ok, col_dist.at[c].set(val), col_dist)
        d = jnp.where(ok, d.at[r, :].set(_BIG_NEG).at[:, c].set(_BIG_NEG), d)
        return d, col_match, col_dist

    init = (dist, jnp.full((M,), -1, jnp.int32), jnp.zeros((M,), dist.dtype))
    _, col_match, col_dist = jax.lax.fori_loop(0, steps, body, init)
    return col_match, col_dist


def _match(dist, match_type, threshold):
    """bipartite (+ optional per-prediction threshold fill)."""
    col_match, col_dist = _bipartite_greedy(dist)
    if match_type == 'per_prediction':
        best_row = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_val = jnp.max(dist, axis=0)
        fill = (col_match < 0) & (best_val > threshold)
        col_match = jnp.where(fill, best_row, col_match)
        col_dist = jnp.where(fill, best_val, col_dist)
    return col_match, col_dist


@register('iou_similarity')
def _iou_similarity(ins, attrs, ctx):
    """reference operators/detection/iou_similarity_op.cc."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    return {'Out': _iou(x, y)}


@register('bipartite_match')
def _bipartite_match(ins, attrs, ctx):
    dist = data_of(ins['DistMat'][0])
    mt = attrs.get('match_type', 'bipartite')
    thr = float(attrs.get('dist_threshold', 0.5))
    if dist.ndim == 2:
        m, d = _match(dist, mt, thr)
        return {'ColToRowMatchIndices': m[None], 'ColToRowMatchDist': d[None]}
    m, d = jax.vmap(lambda x: _match(x, mt, thr))(dist)
    return {'ColToRowMatchIndices': m, 'ColToRowMatchDist': d}


@register('target_assign')
def _target_assign(ins, attrs, ctx):
    """Gather per-prior targets by match index (reference
    operators/detection/target_assign_op.cc); mismatch rows get
    mismatch_value with weight 0."""
    x = ins['X'][0]
    xd = data_of(x)                       # [B, N, K]
    match = data_of(ins['MatchIndices'][0])   # [B, M]
    mval = attrs.get('mismatch_value', 0)

    def one(xb, mb):
        safe = jnp.maximum(mb, 0)
        out = xb[safe]                    # [M, K]
        ok = (mb >= 0)[:, None]
        return jnp.where(ok, out, mval), ok.astype(jnp.float32)

    out, w = jax.vmap(one)(xd, match)
    return {'Out': out, 'OutWeight': w}


def _nms_class(iou_all, scores, nms_threshold, score_threshold, nms_top_k,
               nms_eta=1.0):
    """Single-class NMS: returns keep mask [M] (top nms_top_k by score,
    greedy IoU suppression). iou_all is the class-shared [M, M] IoU matrix
    (computed once per image); the sequential suppression runs as a
    fori_loop over the score-sorted candidates. The adaptive threshold
    (nms_eta < 1) decays only after a kept box while thr > 0.5, matching
    the reference multiclass_nms_op."""
    M = scores.shape[0]
    k = min(nms_top_k, M) if nms_top_k > 0 else M
    order = jnp.argsort(-scores)
    ss = scores[order]
    iou = iou_all[order][:, order]
    valid = ss > score_threshold

    def body(i, carry):
        keep, suppressed, thr = carry
        cur = valid[i] & ~suppressed[i]
        keep = keep.at[i].set(cur)
        later = jnp.arange(M) > i
        suppressed = suppressed | (cur & later & (iou[i] > thr))
        thr = jnp.where((nms_eta < 1.0) & cur & (thr > 0.5), thr * nms_eta,
                        thr)
        return keep, suppressed, thr

    keep, _, _ = jax.lax.fori_loop(
        0, k, body, (jnp.zeros((M,), bool), jnp.zeros((M,), bool),
                     jnp.asarray(nms_threshold, jnp.float32)))
    # un-sort the keep mask
    inv = jnp.zeros((M,), jnp.int32).at[order].set(jnp.arange(M, dtype=jnp.int32))
    return keep[inv]


@register('multiclass_nms')
def _multiclass_nms(ins, attrs, ctx):
    """reference operators/detection/multiclass_nms_op.cc.

    TPU redesign: output is DENSE [B, keep_top_k, 6] (label, score, box),
    padded with label=-1 rows — the reference emits a variable-length
    LoDTensor, a dynamic shape XLA can't compile.
    """
    bboxes = data_of(ins['BBoxes'][0])    # [B, M, 4]
    scores = data_of(ins['Scores'][0])
    M = bboxes.shape[1]
    # layout is declared by the caller ('BCM' is the reference canonical;
    # detection_output passes 'BMC') — no shape sniffing, which would
    # misread canonical input whenever C == M
    if attrs.get('scores_layout', 'BCM') == 'BMC':
        scores = jnp.swapaxes(scores, 1, 2)   # -> [B, C, M]
    C = scores.shape[1]
    bg = int(attrs.get('background_label', 0))
    nms_thr = float(attrs.get('nms_threshold', 0.3))
    score_thr = float(attrs.get('score_threshold', 0.01))
    nms_top_k = int(attrs.get('nms_top_k', 400))
    keep_top_k = int(attrs.get('keep_top_k', 200))
    nms_eta = float(attrs.get('nms_eta', 1.0))

    classes = [c for c in range(C) if c != bg]

    def one(boxes, sc):
        iou_all = _iou(boxes, boxes)     # shared across classes
        cls_scores = sc[jnp.asarray(classes)]        # [C', M]
        keep = jax.vmap(lambda s_c: _nms_class(
            iou_all, s_c, nms_thr, score_thr, nms_top_k,
            nms_eta))(cls_scores)                    # [C', M]
        all_scores = jnp.where(keep, cls_scores, -1.0).reshape(-1)
        all_labels = jnp.repeat(jnp.asarray(classes, jnp.float32), M)
        all_boxes = jnp.tile(boxes, (len(classes), 1))
        k = min(keep_top_k, all_scores.shape[0])
        top = jnp.argsort(-all_scores)[:k]
        ts, tl, tb = all_scores[top], all_labels[top], all_boxes[top]
        ok = ts > 0
        row = jnp.concatenate([jnp.where(ok, tl, -1.0)[:, None],
                               jnp.where(ok, ts, 0.0)[:, None],
                               jnp.where(ok[:, None], tb, 0.0)], axis=1)
        if k < keep_top_k:
            row = jnp.pad(row, ((0, keep_top_k - k), (0, 0)),
                          constant_values=-1.0)
        return row

    return {'Out': jax.vmap(one)(bboxes, scores)}


@register('anchor_generator')
def _anchor_generator(ins, attrs, ctx):
    """reference operators/detection/anchor_generator_op.cc."""
    feat = data_of(ins['Input'][0])       # NCHW
    sizes = list(attrs.get('anchor_sizes', [64.0]))
    ars = list(attrs.get('aspect_ratios', [1.0]))
    variances = list(attrs.get('variances', [0.1, 0.1, 0.2, 0.2]))
    stride = list(attrs.get('stride', [16.0, 16.0]))
    offset = float(attrs.get('offset', 0.5))
    fh, fw = feat.shape[2], feat.shape[3]
    cx = (jnp.arange(fw) + offset) * stride[0]
    cy = (jnp.arange(fh) + offset) * stride[1]
    cxg, cyg = jnp.meshgrid(cx, cy)
    shapes = []
    for ar in ars:
        for s in sizes:
            w = s * np.sqrt(ar)
            h = s / np.sqrt(ar)
            shapes.append((w / 2.0, h / 2.0))
    out = jnp.stack([jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1)
                     for w, h in shapes], axis=2)   # [fh, fw, A, 4]
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           out.shape[:-1] + (4,))
    return {'Anchors': out, 'Variances': var}


def _encode_boxes(gt, priors, pvar):
    """center-size encode gt [*, 4] against priors [*, 4]."""
    pw = priors[..., 2] - priors[..., 0]
    ph = priors[..., 3] - priors[..., 1]
    pcx = priors[..., 0] + 0.5 * pw
    pcy = priors[..., 1] + 0.5 * ph
    gw = jnp.maximum(gt[..., 2] - gt[..., 0], 1e-6)
    gh = jnp.maximum(gt[..., 3] - gt[..., 1], 1e-6)
    gcx = gt[..., 0] + 0.5 * gw
    gcy = gt[..., 1] + 0.5 * gh
    return jnp.stack([(gcx - pcx) / pw / pvar[..., 0],
                      (gcy - pcy) / ph / pvar[..., 1],
                      jnp.log(gw / pw) / pvar[..., 2],
                      jnp.log(gh / ph) / pvar[..., 3]], axis=-1)


@register('ssd_loss')
def _ssd_loss(ins, attrs, ctx):
    """Fused SSD loss (reference layers/detection.py:ssd_loss:562 — there a
    13-op chain of iou_similarity/bipartite_match/target_assign/
    mine_hard_examples; here ONE dense rule, XLA fuses the lot).

    Per image: per-prediction matching, smooth-L1 on matched localizations,
    softmax CE on class scores, max-negative hard mining at neg_pos_ratio.
    Out: per-image loss [B, 1] summed over priors and normalized by the
    batch-global positive count (reference divides by
    reduce_sum(target_loc_weight), i.e. total positives across the batch).
    """
    from ..lowering import SeqValue
    loc = data_of(ins['Loc'][0])          # [B, P, 4]
    conf = data_of(ins['Conf'][0])        # [B, P, C]
    gt_box_v = ins['GtBox'][0]
    gt_lbl_v = ins['GtLabel'][0]
    gt_box = data_of(gt_box_v)            # [B, G, 4]
    gt_lbl = data_of(gt_lbl_v).reshape(gt_box.shape[0], -1)  # [B, G]
    lengths = (gt_box_v.lengths if isinstance(gt_box_v, SeqValue)
               else jnp.full((gt_box.shape[0],), gt_box.shape[1], jnp.int32))
    priors = data_of(ins['PriorBox'][0]).reshape(-1, 4)       # [P, 4]
    pvar = (data_of(ins['PriorBoxVar'][0]).reshape(-1, 4)
            if ins.get('PriorBoxVar') else jnp.ones_like(priors))
    bg = int(attrs.get('background_label', 0))
    overlap_t = float(attrs.get('overlap_threshold', 0.5))
    neg_ratio = float(attrs.get('neg_pos_ratio', 3.0))
    neg_overlap = float(attrs.get('neg_overlap', 0.5))
    loc_w = float(attrs.get('loc_loss_weight', 1.0))
    conf_w = float(attrs.get('conf_loss_weight', 1.0))
    match_type = attrs.get('match_type', 'per_prediction')
    normalize = bool(attrs.get('normalize', True))
    G = gt_box.shape[1]

    def one(loc_b, conf_b, gtb, gtl, n_gt):
        valid_gt = jnp.arange(G) < n_gt
        raw_iou = _iou(gtb, priors)                   # [G, P]
        dist = jnp.where(valid_gt[:, None], raw_iou, _BIG_NEG)
        match, _ = _match(dist, match_type, overlap_t)   # [P]
        pos = match >= 0
        n_pos = pos.sum()
        safe = jnp.maximum(match, 0)
        matched_gt = gtb[safe]                        # [P, 4]
        loc_target = _encode_boxes(matched_gt, priors, pvar)
        diff = loc_b - loc_target
        ad = jnp.abs(diff)
        smooth = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(-1)
        loc_loss = smooth * pos

        labels = jnp.where(pos, gtl[safe].astype(jnp.int32), bg)
        logp = jax.nn.log_softmax(conf_b, axis=-1)
        ce = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        # max-negative mining: only priors whose best overlap is below
        # neg_overlap are eligible (the reference excludes ambiguous
        # [neg_overlap, overlap_threshold) priors); rank by conf loss
        best_iou = jnp.max(jnp.where(valid_gt[:, None], raw_iou, 0.0), axis=0)
        neg_cand = (~pos) & (best_iou < neg_overlap)
        neg_loss = jnp.where(neg_cand, ce, -jnp.inf)
        n_neg = jnp.minimum((neg_ratio * n_pos).astype(jnp.int32),
                            neg_cand.sum())
        rank = jnp.argsort(jnp.argsort(-neg_loss))
        neg_sel = neg_cand & (rank < n_neg)
        conf_loss = ce * (pos | neg_sel)
        total = loc_w * loc_loss + conf_w * conf_loss
        return total, n_pos

    loss, n_pos = jax.vmap(one)(loc, conf, gt_box, gt_lbl, lengths)
    loss_img = loss.sum(axis=1)                       # [B]
    if normalize:
        total_pos = n_pos.sum().astype(loss_img.dtype)
        loss_img = loss_img / jnp.maximum(total_pos, 1.0)
    return {'Loss': loss_img[:, None]}  # [B, 1], the declared shape


@register('rpn_target_assign')
def _rpn_target_assign(ins, attrs, ctx):
    """reference layers/detection.py:rpn_target_assign:56. Dense form:
    fixed rpn_batch_size_per_im samples per image, label -1 marks unused
    slots (the reference gathers variable-size sampled index lists)."""
    from ..lowering import SeqValue
    loc = data_of(ins['Loc'][0])          # [B, A, 4]
    scores = data_of(ins['Score'][0])     # [B, A, 1]
    anchors = data_of(ins['AnchorBox'][0]).reshape(-1, 4)     # [A, 4]
    gt_v = ins['GtBox'][0]
    gt = data_of(gt_v)                    # [B, G, 4]
    lengths = (gt_v.lengths if isinstance(gt_v, SeqValue)
               else jnp.full((gt.shape[0],), gt.shape[1], jnp.int32))
    S = int(attrs.get('rpn_batch_size_per_im', 256))
    fg_frac = float(attrs.get('fg_fraction', 0.25))
    pos_t = float(attrs.get('rpn_positive_overlap', 0.7))
    neg_t = float(attrs.get('rpn_negative_overlap', 0.3))
    G = gt.shape[1]
    n_fg = int(S * fg_frac)

    def one(loc_b, sc_b, gtb, n_gt):
        valid_gt = jnp.arange(G) < n_gt
        iou = _iou(gtb, anchors)                     # [G, A]
        iou = jnp.where(valid_gt[:, None], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=0)            # per anchor
        best_iou = jnp.max(iou, axis=0)
        # positives: iou > pos_t, plus the best anchor of every gt
        pos = best_iou > pos_t
        best_anchor = jnp.argmax(iou, axis=1)        # [G]
        # duplicate indices (padded gt rows all argmax to 0) must OR, not
        # race: .max() is the deterministic scatter-or
        pos = pos.at[best_anchor].max(valid_gt)
        neg = (best_iou < neg_t) & ~pos
        # deterministic sampling: top-iou positives, lowest-iou negatives
        pos_rank = jnp.argsort(jnp.argsort(-jnp.where(pos, best_iou, -1.0)))
        pos_sel = pos & (pos_rank < n_fg)
        n_pos_sel = pos_sel.sum()
        n_neg = S - n_pos_sel
        neg_rank = jnp.argsort(jnp.argsort(jnp.where(neg, best_iou, 2.0)))
        neg_sel = neg & (neg_rank < n_neg)
        sel = pos_sel | neg_sel
        idx = jnp.argsort(~sel)[:S]              # selected slots first
        tgt_box = _encode_boxes(gtb[best_gt], anchors, jnp.ones_like(anchors))
        lbl = jnp.where(pos_sel, 1, jnp.where(neg_sel, 0, -1))
        return (sc_b[idx], loc_b[idx], lbl[idx][:, None],
                tgt_box[idx])

    ps, pl, tl, tb = jax.vmap(one)(loc, scores, gt, lengths)
    return {'PredScore': ps, 'PredLoc': pl, 'TargetLabel': tl,
            'TargetBox': tb}


@register('detection_map')
def _detection_map(ins, attrs, ctx):
    """Integral-AP mAP metric (reference operators/detection/
    detection_map_op.cc), stateless per batch. DetectRes is the dense
    multiclass_nms output [B, K, 6]; Label is [B, G, 5] (label, box) padded
    SeqValue."""
    from ..lowering import SeqValue
    det = data_of(ins['DetectRes'][0])    # [B, K, 6]
    lab_v = ins['Label'][0]
    lab = data_of(lab_v)                  # [B, G, >=5]
    B, G = lab.shape[0], lab.shape[1]
    lengths = (lab_v.lengths if isinstance(lab_v, SeqValue)
               else jnp.full((B,), G, jnp.int32))
    C = int(attrs['class_num'])
    bg = int(attrs.get('background_label', 0))
    thr = float(attrs.get('overlap_threshold', 0.3))
    if attrs.get('ap_type', 'integral') != 'integral':
        raise ValueError("detection_map: only ap_version='integral' is "
                         "implemented")
    K = det.shape[1]

    gt_valid = jnp.arange(G)[None, :] < lengths[:, None]      # [B, G]
    gt_label = lab[..., 0]
    gt_box = lab[..., 1:5]

    aps = []
    for c in range(C):
        if c == bg:
            continue
        det_ok = det[..., 0] == c                              # [B, K]
        scores = jnp.where(det_ok, det[..., 1], -1.0)
        gt_c = gt_valid & (gt_label == c)                      # [B, G]
        n_gt = gt_c.sum()

        flat_scores = scores.reshape(-1)                       # [B*K]
        order = jnp.argsort(-flat_scores)

        def body(i, carry):
            used, tp, fp = carry
            fi = order[i]
            b, k = fi // K, fi % K
            valid = flat_scores[fi] > 0
            iou = _iou(det[b, k, 2:6][None], gt_box[b])[0]     # [G]
            iou = jnp.where(gt_c[b] & ~used[b], iou, -1.0)
            j = jnp.argmax(iou)
            hit = valid & (iou[j] >= thr)
            used = jnp.where(hit, used.at[b, j].set(True), used)
            tp = tp.at[i].set(jnp.where(valid & hit, 1.0, 0.0))
            fp = fp.at[i].set(jnp.where(valid & ~hit, 1.0, 0.0))
            return used, tp, fp

        used0 = jnp.zeros((B, G), bool)
        n = B * K
        _, tp, fp = jax.lax.fori_loop(
            0, n, body, (used0, jnp.zeros((n,)), jnp.zeros((n,))))
        ctp = jnp.cumsum(tp)
        cfp = jnp.cumsum(fp)
        precision = ctp / jnp.maximum(ctp + cfp, 1e-10)
        # integral AP: sum precision * delta-recall over detections
        ap = jnp.sum(precision * tp) / jnp.maximum(n_gt, 1)
        aps.append(jnp.where(n_gt > 0, ap, jnp.nan))

    aps = jnp.stack(aps)
    valid = ~jnp.isnan(aps)
    mean_ap = jnp.where(valid, aps, 0.0).sum() / jnp.maximum(valid.sum(), 1)
    return {'MAP': mean_ap}
