"""Detection (SSD family) rules.

Parity: reference paddle/fluid/operators/detection/*. Implemented as masked
dense JAX; the handful that are inherently host-side dynamic (NMS output
lists) return fixed-size padded results with validity counts.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of


@register('prior_box')
def _prior_box(ins, attrs, ctx):
    """reference operators/detection/prior_box_op.cc."""
    feat = data_of(ins['Input'][0])  # NCHW feature map
    img = data_of(ins['Image'][0])
    min_sizes = list(attrs['min_sizes'])
    max_sizes = list(attrs.get('max_sizes', []) or [])
    ars = list(attrs.get('aspect_ratios', [1.0]))
    flip = attrs.get('flip', False)
    variances = list(attrs.get('variances', [0.1, 0.1, 0.2, 0.2]))
    clip = attrs.get('clip', False)
    step_w = attrs.get('step_w', 0.0)
    step_h = attrs.get('step_h', 0.0)
    offset = attrs.get('offset', 0.5)

    full_ars = [1.0]
    for ar in ars:
        if abs(ar - 1.0) < 1e-6:
            continue
        full_ars.append(ar)
        if flip:
            full_ars.append(1.0 / ar)

    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    sw = step_w if step_w > 0 else iw / fw
    sh = step_h if step_h > 0 else ih / fh

    boxes = []
    for ms in min_sizes:
        for ar in full_ars:
            bw = ms * np.sqrt(ar) / 2.0
            bh = ms / np.sqrt(ar) / 2.0
            boxes.append((bw, bh))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            s = np.sqrt(ms * mx) / 2.0
            boxes.append((s, s))
    num_priors = len(boxes)
    cx = (jnp.arange(fw) + offset) * sw
    cy = (jnp.arange(fh) + offset) * sh
    cxg, cyg = jnp.meshgrid(cx, cy)  # [fh, fw]
    out = []
    for bw, bh in boxes:
        b = jnp.stack([(cxg - bw) / iw, (cyg - bh) / ih,
                       (cxg + bw) / iw, (cyg + bh) / ih], axis=-1)
        out.append(b)
    out = jnp.stack(out, axis=2)  # [fh, fw, np, 4]
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           out.shape[:-1] + (4,))
    return {'Boxes': out, 'Variances': var}


@register('box_coder')
def _box_coder(ins, attrs, ctx):
    """reference operators/detection/box_coder_op.cc (decode_center_size)."""
    prior = data_of(ins['PriorBox'][0])  # [M, 4]
    pvar = data_of(ins['PriorBoxVar'][0]) if ins.get('PriorBoxVar') else None
    target = data_of(ins['TargetBox'][0])
    code_type = attrs.get('code_type', 'decode_center_size')
    pw = prior[:, 2] - prior[:, 0]
    ph = prior[:, 3] - prior[:, 1]
    pcx = prior[:, 0] + 0.5 * pw
    pcy = prior[:, 1] + 0.5 * ph
    if pvar is None:
        pvar = jnp.ones_like(prior)
    if 'decode' in code_type:
        # target: [N, M, 4]
        tcx = pvar[..., 0] * target[..., 0] * pw + pcx
        tcy = pvar[..., 1] * target[..., 1] * ph + pcy
        tw = jnp.exp(pvar[..., 2] * target[..., 2]) * pw
        th = jnp.exp(pvar[..., 3] * target[..., 3]) * ph
        out = jnp.stack([tcx - tw / 2, tcy - th / 2,
                         tcx + tw / 2, tcy + th / 2], axis=-1)
    else:
        # encode: target [N, 4] gt boxes vs priors [M, 4] -> [N, M, 4]
        gw = target[:, None, 2] - target[:, None, 0]
        gh = target[:, None, 3] - target[:, None, 1]
        gcx = target[:, None, 0] + 0.5 * gw
        gcy = target[:, None, 1] + 0.5 * gh
        out = jnp.stack([
            (gcx - pcx[None]) / pw[None] / pvar[None, :, 0],
            (gcy - pcy[None]) / ph[None] / pvar[None, :, 1],
            jnp.log(gw / pw[None]) / pvar[None, :, 2],
            jnp.log(gh / ph[None]) / pvar[None, :, 3]], axis=-1)
    return {'OutputBox': out}
