"""Elementwise / activation / reduction / matmul rules.

Parity: reference paddle/fluid/operators/{elementwise_*,activation,reduce_*,
mul,matmul,sum,mean,clip,compare,logical}_op.* — one JAX rule each; XLA fuses
them into surrounding matmuls (the reference launches a CUDA kernel per op).
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like, first_seq, amp_cast, SeqValue


def _seq_pad_mask(v):
    """Broadcastable [batch, max_len, 1...] validity mask for a SeqValue."""
    m = v.mask()
    while m.ndim < v.data.ndim:
        m = m[..., None]
    return m


def _unary(op_type, fn):
    @register(op_type)
    def rule(ins, attrs, ctx, _fn=fn):
        x = ins['X'][0]
        return {'Out': like(x, _fn(data_of(x), attrs))}
    return rule


# 26 generated activations (reference python/paddle/fluid/layers/ops.py
# __activations__) + relu & friends.
_unary('sigmoid', lambda x, a: jax.nn.sigmoid(x))
_unary('logsigmoid', lambda x, a: jax.nn.log_sigmoid(x))
_unary('exp', lambda x, a: jnp.exp(x))
_unary('tanh', lambda x, a: jnp.tanh(x))
_unary('tanh_shrink', lambda x, a: x - jnp.tanh(x))
_unary('softshrink', lambda x, a: jnp.sign(x) * jnp.maximum(jnp.abs(x) - a.get('lambda', 0.5), 0.0))
_unary('sqrt', lambda x, a: jnp.sqrt(x))
_unary('abs', lambda x, a: jnp.abs(x))
_unary('ceil', lambda x, a: jnp.ceil(x))
_unary('floor', lambda x, a: jnp.floor(x))
_unary('cos', lambda x, a: jnp.cos(x))
_unary('sin', lambda x, a: jnp.sin(x))
_unary('round', lambda x, a: jnp.round(x))
_unary('reciprocal', lambda x, a: 1.0 / x)
_unary('square', lambda x, a: jnp.square(x))
_unary('softplus', lambda x, a: jax.nn.softplus(x))
_unary('softsign', lambda x, a: x / (1 + jnp.abs(x)))
_unary('brelu', lambda x, a: jnp.clip(x, a.get('t_min', 0.0), a.get('t_max', 24.0)))
_unary('leaky_relu', lambda x, a: jnp.where(x >= 0, x, a.get('alpha', 0.02) * x))
_unary('soft_relu', lambda x, a: jnp.log1p(jnp.exp(jnp.clip(x, -a.get('threshold', 40.0), a.get('threshold', 40.0)))))
_unary('elu', lambda x, a: jnp.where(x >= 0, x, a.get('alpha', 1.0) * (jnp.exp(x) - 1)))
_unary('relu6', lambda x, a: jnp.clip(x, 0.0, a.get('threshold', 6.0)))
_unary('pow', lambda x, a: jnp.power(x, a.get('factor', 1.0)))
_unary('stanh', lambda x, a: a.get('scale_b', 1.7159) * jnp.tanh(a.get('scale_a', 2.0 / 3.0) * x))
_unary('hard_sigmoid', lambda x, a: jnp.clip(a.get('slope', 0.2) * x + a.get('offset', 0.5), 0.0, 1.0))
_unary('swish', lambda x, a: x * jax.nn.sigmoid(a.get('beta', 1.0) * x))
_unary('hard_shrink', lambda x, a: jnp.where(
    jnp.abs(x) > a.get('threshold', 0.5), x, 0.0))
_unary('thresholded_relu', lambda x, a: jnp.where(
    x > a.get('threshold', 1.0), x, 0.0))
_unary('relu', lambda x, a: jnp.maximum(x, 0))
_unary('log', lambda x, a: jnp.log(x))
_unary('logical_not', lambda x, a: jnp.logical_not(x))
_unary('clip', lambda x, a: jnp.clip(x, a['min'], a['max']))
_unary('scale', lambda x, a: (x + a.get('bias', 0.0)) * a['scale']
       if a.get('bias_after_scale', True) is False
       else x * a['scale'] + a.get('bias', 0.0))


@register('clip_by_norm')
def _clip_by_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    max_norm = attrs['max_norm']
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12), 1.0)
    return {'Out': like(ins['X'][0], x * scale)}


def _broadcast_y(x, y, axis):
    """Fluid elementwise broadcast: align y's dims to x starting at `axis`
    (reference operators/elementwise_op_function.h)."""
    if x.ndim <= y.ndim:
        # same rank, or x is lower-rank (e.g. scalar op [1]-vector): plain
        # numpy broadcasting applies and there is no trailing-dim alignment
        return y
    if axis == -1:
        axis = x.ndim - y.ndim
    shape = [1] * x.ndim
    for i, d in enumerate(y.shape):
        shape[axis + i] = d
    return y.reshape(shape)


def _binary(op_type, fn):
    @register(op_type)
    def rule(ins, attrs, ctx, _fn=fn):
        xv, yv = ins['X'][0], ins['Y'][0]
        x, y = data_of(xv), data_of(yv)
        y = _broadcast_y(x, y, attrs.get('axis', -1))
        seq = first_seq(xv, yv)
        out = _fn(x, y)
        return {'Out': like(seq, out) if seq is not None else out}
    return rule


_binary('elementwise_add', jnp.add)
_binary('elementwise_sub', jnp.subtract)
_binary('elementwise_mul', jnp.multiply)
_binary('elementwise_div', jnp.divide)
_binary('elementwise_max', jnp.maximum)
_binary('elementwise_min', jnp.minimum)
_binary('elementwise_pow', jnp.power)
_binary('logical_and', jnp.logical_and)
_binary('logical_or', jnp.logical_or)
_binary('logical_xor', jnp.logical_xor)
_binary('less_than', lambda x, y: jnp.less(x, y))
_binary('less_equal', jnp.less_equal)
_binary('greater_than', jnp.greater)
_binary('greater_equal', jnp.greater_equal)
_binary('equal', jnp.equal)
_binary('not_equal', jnp.not_equal)


@register('mul')
def _mul(ins, attrs, ctx):
    """reference operators/mul_op.cc: flatten x to 2-D at x_num_col_dims and
    y at y_num_col_dims, then matmul. On TPU this IS the MXU op."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    xn = attrs.get('x_num_col_dims', 1)
    yn = attrs.get('y_num_col_dims', 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xn])), int(np.prod(xs[xn:]))))
    y2 = y.reshape((int(np.prod(ys[:yn])), int(np.prod(ys[yn:]))))
    in_dtype = x.dtype
    x2, y2 = amp_cast(ctx, x2, y2)
    out = jnp.matmul(
        x2, y2,
        preferred_element_type=jnp.float32 if x2.dtype == jnp.bfloat16
        else None).astype(in_dtype)
    out = out.reshape(xs[:xn] + ys[yn:])
    return {'Out': like(ins['X'][0], out)}


@register('matmul')
def _matmul(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    if attrs.get('transpose_X', False):
        x = jnp.swapaxes(x, -1, -2)
    if attrs.get('transpose_Y', False):
        y = jnp.swapaxes(y, -1, -2)
    in_dtype = x.dtype
    x, y = amp_cast(ctx, x, y)
    out = jnp.matmul(
        x, y,
        preferred_element_type=jnp.float32 if x.dtype == jnp.bfloat16
        else None).astype(in_dtype) * attrs.get('alpha', 1.0)
    return {'Out': out}


def _reduce_pad_fill(op_type, dtype):
    if op_type in ('reduce_sum', 'reduce_mean'):
        return jnp.asarray(0, dtype)
    if op_type == 'reduce_prod':
        return jnp.asarray(1, dtype)
    lo_hi = (jnp.iinfo(dtype) if jnp.issubdtype(dtype, jnp.integer)
             else jnp.finfo(dtype))
    return jnp.asarray(lo_hi.min if op_type == 'reduce_max' else lo_hi.max,
                       dtype)


def _reduce(op_type, fn):
    @register(op_type)
    def rule(ins, attrs, ctx, _fn=fn, _op=op_type):
        xv = ins['X'][0]
        x = data_of(xv)
        dim = attrs.get('dim')
        keep = attrs.get('keep_dim', False)
        if attrs.get('reduce_all', False) or dim is None:
            axis = None
        else:
            axis = tuple(dim) if isinstance(dim, (list, tuple)) else (dim,)
        # padded positions must not contaminate a reduction that crosses
        # the time axis (axis 1 of the dense [B, T, ...] layout); a
        # reduction over other axes keeps the sequence layout, where pads
        # stay pads and must NOT be replaced by ±extremes
        reduces_time = axis is None or any(a % x.ndim == 1 for a in axis)
        if isinstance(xv, SeqValue) and reduces_time:
            mask = _seq_pad_mask(xv)
            x = jnp.where(mask > 0, x, _reduce_pad_fill(_op, x.dtype))
            if _op == 'reduce_mean':
                n = jnp.sum(jnp.broadcast_to(mask, x.shape).astype(x.dtype),
                            axis=axis, keepdims=keep)
                return {'Out': jnp.sum(x, axis=axis, keepdims=keep)
                        / jnp.maximum(n, 1)}
        out = _fn(x, axis=axis, keepdims=keep)
        if isinstance(xv, SeqValue) and not reduces_time \
                and out.ndim >= 2 and out.shape[:2] == x.shape[:2]:
            return {'Out': like(xv, out)}   # still [B, T, ...]: keep lengths
        return {'Out': out}
    return rule


_reduce('reduce_sum', jnp.sum)
_reduce('reduce_mean', jnp.mean)
_reduce('reduce_max', jnp.max)
_reduce('reduce_min', jnp.min)
_reduce('reduce_prod', jnp.prod)


@register('mean')
def _mean(ins, attrs, ctx):
    xv = ins['X'][0]
    x = data_of(xv)
    if isinstance(xv, SeqValue):
        # average over VALID tokens only (reference mean sees the flattened
        # LoDTensor, which has no pad rows at all — lod_tensor.h)
        mask = jnp.broadcast_to(_seq_pad_mask(xv), x.shape)
        # shape [1], not 0-d: reference mean_op's output dims are {1}
        # (mean_op.cc InferShape) and verbatim reference scripts index
        # the fetched loss as avg_loss_value[0]
        return {'Out': (jnp.sum(x * mask)
                        / jnp.maximum(jnp.sum(mask), 1)).reshape(1)}
    return {'Out': jnp.mean(x).reshape(1)}


@register('sum')
def _sum(ins, attrs, ctx):
    xs = [data_of(v) for v in ins['X']]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {'Out': like(first_seq(*ins['X']), out)}


@register('maxout')
def _maxout(ins, attrs, ctx):
    x = data_of(ins['X'][0])  # NCHW
    g = attrs['groups']
    n, c, h, w = x.shape
    return {'Out': x.reshape(n, c // g, g, h, w).max(axis=2)}


@register('cos_sim')
def _cos_sim(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    out = jnp.sum(x * y, axis=-1, keepdims=True) / jnp.maximum(xn * yn, 1e-12)
    return {'Out': out, 'XNorm': xn, 'YNorm': yn}


@register('l2_normalize')
def _l2_normalize(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axis = attrs.get('axis', -1)
    eps = attrs.get('epsilon', 1e-12)
    norm = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=True))
    norm = jnp.maximum(norm, eps)
    return {'Out': like(ins['X'][0], x / norm), 'Norm': norm}
