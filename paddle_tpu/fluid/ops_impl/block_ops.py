"""Structured control flow: sub-block ops lowered to XLA control flow.

Parity: reference paddle/fluid/operators/{while_op.cc, conditional_block_op.cc,
recurrent_op.cc, array_write_op.cc (LoDTensorArray)} and the Python-side
layers/control_flow.py While/Switch/IfElse/StaticRNN/DynamicRNN.

TPU-first redesign: the reference interprets sub-blocks in fresh C++ scopes
(one scope per loop iteration, kept alive for the backward pass). Under XLA
everything is one traced computation, so:
  - `while`      -> lax.while_loop over an explicit carry dict (or a bounded
                    lax.scan with predicated updates when max_iters is given,
                    which keeps the loop differentiable);
  - `static_rnn` -> lax.scan over the leading (time) axis;
  - `dynamic_rnn`-> lax.scan over padded [batch, T, ...] sequences with
                    per-sequence length masking of memory updates;
  - `ifelse`/`switch` -> both branches execute, outputs merged by predicated
                    select (XLA's branch-free equivalent; cheap on TPU where
                    divergent control flow would stall the vector units).
LoDTensorArray becomes a fixed-capacity buffer + live length (ArrayValue),
making arrays legal loop carries.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import (register, register_block_op, run_block, data_of,
                        ArrayValue, SeqValue, Ctx, DEFAULT_ARRAY_CAPACITY)


def _scalar_bool(c):
    c = data_of(c)
    return jnp.reshape(c, (-1,))[0].astype(bool)


def _iter_ctx(ctx, t):
    """Fold the loop-iteration counter into the PRNG key so random ops
    (dropout etc.) inside loop bodies draw fresh bits every step."""
    return Ctx(jax.random.fold_in(ctx.key, t), is_test=ctx.is_test,
               amp=ctx.amp, platform=ctx.platform, mesh=ctx.mesh,
               manual_axes=ctx.manual_axes)


def _pred_where(cond, a, b):
    """Predicated merge with ndim alignment (cond may be [N,1] vs val [N,D])."""
    def one(x, y):
        c = cond
        while c.ndim > x.ndim:
            c = jnp.squeeze(c, -1)
        while c.ndim < x.ndim:
            c = c[..., None]
        return jnp.where(c, x, y)
    return jax.tree_util.tree_map(one, a, b)


# ---------------------------------------------------------------------------
# LoDTensorArray ops
# ---------------------------------------------------------------------------

@register('array_write')
def _array_write(ins, attrs, ctx):
    x = ins['X'][0]
    if not isinstance(x, SeqValue):
        x = data_of(x)
    i = jnp.reshape(data_of(ins['I'][0]), (-1,))[0].astype(jnp.int32)
    arrs = ins.get('Array', [])
    if arrs and isinstance(arrs[0], ArrayValue):
        arr = arrs[0]
    else:
        cap = int(attrs.get('capacity', DEFAULT_ARRAY_CAPACITY))
        arr = ArrayValue.fresh(x, cap)
    # Writes past capacity clamp to the last slot (dynamic_update_index
    # semantics); length is clamped too so reads stay in range. Size the
    # array via create_array/array_write(capacity=) for longer loops.
    cap = (arr.buffer[0] if arr.is_seq else arr.buffer).shape[0]
    lax.cond(i >= cap,
             lambda: jax.debug.print(
                 'WARNING: array_write index {i} >= capacity {c}; write '
                 'clamped to the last slot — pass capacity= to '
                 'create_array/array_write for longer loops', i=i, c=cap),
             lambda: None)
    return {'Out': arr.write(i, x)}


@register('array_read')
def _array_read(ins, attrs, ctx):
    arr = ins['Array'][0]
    i = jnp.reshape(data_of(ins['I'][0]), (-1,))[0].astype(jnp.int32)
    return {'Out': arr.read(i)}


@register('array_length')
def _array_length(ins, attrs, ctx):
    arr = ins['Array'][0]
    return {'Out': jnp.reshape(arr.length, (1,)).astype(jnp.int64)}


@register('array_stack')
def _array_stack(ins, attrs, ctx):
    """Materialize a LoDTensorArray as one [capacity, ...] stacked tensor
    (extension backing contrib's BeamSearchDecoder; the reference walks the
    LoDTensorArray on the host instead). Slots never written are zeros —
    size the array's capacity to the loop trip count."""
    arr = ins['Array'][0]
    return {'Out': arr.buffer[0] if arr.is_seq else arr.buffer}


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

# single source of truth for the stride-widening convention (rows move to
# block starts): ArrayValue._grow_rows in lowering.py
_widen_rows = ArrayValue._grow_rows


def _widen_array(a, target):
    """Widen an initial ArrayValue to the shapes/structure the loop body
    produces (`target` is the eval_shape result, an ArrayValue of
    ShapeDtypeStructs). The result adopts target's beam flag: widening IS
    the capacity-form conversion, and lax.while_loop demands the carry's
    static aux (which the flag is part of) match the body's output."""
    n_src = (target.buffer[2].shape[1]
             if target.is_seq and target.n_outer >= 1 else None)
    if target.is_seq and not a.is_seq:
        # the pre-loop write was dense (e.g. the encoder state fed into
        # state_array); the body writes LoD values. Adopt the seq layout
        # with the dense rows as 1-row-per-source groups.
        data_t, len_t = target.buffer[0], target.buffer[1]
        data = _widen_rows(a.buffer, data_t.shape[1], n_sources=n_src)
        stride = data_t.shape[1] // a.buffer.shape[1]
        lens = jnp.zeros(len_t.shape, len_t.dtype)
        lens = lens.at[:, ::stride].set(
            jnp.ones((len_t.shape[0], a.buffer.shape[1]), len_t.dtype))
        outer = tuple(
            jnp.ones(ob.shape, ob.dtype)
            for ob in target.buffer[2:2 + target.n_outer])
        return ArrayValue((data, lens) + outer, a.length, target.n_outer,
                          beam=target.beam)
    if a.is_seq:
        data_t = target.buffer[0]
        d0 = a.buffer[0]
        if d0.ndim == data_t.ndim + 1 and d0.shape[2] == 1:
            # padded 2-level feed form [B, max_len=1, ...] (the book's
            # init_ids/init_scores) -> flat capacity row form [B, ...]
            d0 = d0.reshape(d0.shape[:2] + d0.shape[3:])
        if d0.shape != data_t.shape:
            data = _widen_rows(d0, data_t.shape[1], n_sources=n_src)
            lens = _widen_rows(a.buffer[1], target.buffer[1].shape[1],
                               n_sources=n_src)
            outer = a.buffer[2:]
            return ArrayValue((data, lens) + outer, a.length, a.n_outer,
                              beam=target.beam)
        if d0 is not a.buffer[0] or a.beam != target.beam:
            return ArrayValue((d0,) + a.buffer[1:], a.length, a.n_outer,
                              beam=target.beam)
        return a
    if a.buffer.shape != target.buffer.shape:
        return ArrayValue(_widen_rows(a.buffer, target.buffer.shape[1]),
                          a.length, -1, beam=target.beam)
    return a


def _widen_carry_to_body(init, body_env):
    """Fixed-point capacity widening (the book's LoD beam decoder idiom):
    pre-loop writes may be narrower than what the body produces — e.g.
    init_ids holds one row per source, beam_search emits beam_size per
    source. lax.while_loop demands identical carry shapes, so abstractly
    evaluate the body and widen the INITIAL arrays to the body's shapes
    (rows redistributed per the beam-block convention) until stable."""
    for _ in range(4):
        try:
            target = jax.eval_shape(body_env, init)
        except Exception:
            return init, False  # let the real trace surface the error
        changed = False
        out = {}
        for n, v in init.items():
            t = target.get(n)
            if isinstance(v, ArrayValue) and isinstance(t, ArrayValue):
                w = _widen_array(v, t)
                changed = changed or (w is not v)
                out[n] = w
            elif (isinstance(v, SeqValue) and isinstance(t, SeqValue)
                  and v.beam_cap != t.beam_cap):
                # the beam flag is static pytree aux: a directly-carried
                # SeqValue the body turns capacity-form must enter the
                # loop with the same aux or lax.while_loop rejects the
                # carry structure
                out[n] = SeqValue(v.data, v.lengths, v.outer_lengths,
                                  beam_cap=t.beam_cap)
                changed = True
            else:
                out[n] = v
        init = out
        if not changed:
            return init, True
    raise ValueError(
        'While: loop-carried shapes did not stabilize after capacity '
        'widening — the body grows an array on every iteration, which '
        'XLA cannot compile; restructure the loop with static shapes')


@register_block_op('while')
def _while(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    cond_name = op.inputs['Condition'][0].name
    carry_names = [v.name for v in op.outputs['Out']]
    missing = [n for n in carry_names if n not in env]
    if missing:
        raise ValueError(
            "While: loop-carried vars %s must be written (e.g. array_write / "
            "fill_constant) before the loop so their shapes are known" % missing)

    outer = dict(env)
    ITER = '__while_iter__'
    init = {n: env[n] for n in carry_names}
    init[ITER] = jnp.asarray(0, jnp.int32)

    def get_cond(carry):
        # The body may not update cond (bounded loops); fall back to the
        # loop-invariant outer value then.
        return carry[cond_name] if cond_name in carry else outer[cond_name]

    def body_env(carry):
        t = carry[ITER]
        e = dict(outer)
        e.update({n: carry[n] for n in carry_names})
        run_block(sub, e, _iter_ctx(ctx, t))
        new = {n: e[n] for n in carry_names}
        new[ITER] = t + 1
        return new

    if any(isinstance(env[n], ArrayValue) for n in carry_names):
        init2, ok = _widen_carry_to_body(init, body_env)
        if ok:
            init = init2

    max_iters = op.attrs.get('max_iters')
    if max_iters:
        # Differentiable bounded form: run max_iters steps, predicate every
        # update on the (pre-step) condition. Grad flows via lax.scan.
        def step(carry, _):
            alive = _scalar_bool(get_cond(carry))
            new = body_env(carry)
            merged = {n: _pred_where(alive, new[n], carry[n])
                      for n in carry_names}
            merged[ITER] = new[ITER]
            return merged, None
        final, _ = lax.scan(step, init, None, length=int(max_iters))
    else:
        final = lax.while_loop(
            lambda c: _scalar_bool(get_cond(c)), body_env, init)
    final.pop(ITER)
    env.update(final)


# ---------------------------------------------------------------------------
# ifelse / switch  (predicated select)
# ---------------------------------------------------------------------------

@register_block_op('ifelse')
def _ifelse(op, env, ctx):
    """Both branches execute; outputs merge via predicated select.

    Gradient hazard (standard JAX where-pitfall): if the UNTAKEN branch
    computes NaN/Inf from inputs the condition was guarding (log/sqrt/div),
    the 0*NaN in its cotangent poisons gradients of shared inputs even
    though the forward value is discarded. Clamp the guarded input inside
    the branch (the double-where trick, e.g. log(where(cond, x, 1.0)))
    so the untaken side stays finite; the reference runs only the taken
    branch and never hits this.
    """
    prog = op.block.program
    t_idx, f_idx = op.attrs['sub_blocks']
    cond = data_of(env[op.inputs['Cond'][0].name])
    te = dict(env)
    run_block(prog.block(t_idx), te, ctx)
    fe = dict(env)
    run_block(prog.block(f_idx), fe, ctx)
    for out_var, tn, fn in zip(op.outputs['Out'], op.attrs['true_outs'],
                               op.attrs['false_outs']):
        env[out_var.name] = _pred_where(cond, data_of(te[tn]),
                                        data_of(fe[fn]))
    # Branch writes to outer-scope vars (e.g. assign(output=outer)) merge
    # too, same as Switch; a var untouched by a branch keeps its pre-if
    # value on that side.
    for v in op.outputs.get('OuterOut', []):
        n = v.name
        env[n] = _pred_where(cond, data_of(te.get(n, env[n])),
                             data_of(fe.get(n, env[n])))


@register_block_op('switch')
def _switch(op, env, ctx):
    prog = op.block.program
    sub_blocks = op.attrs['sub_blocks']
    cond_names = op.attrs['cond_names']   # '' marks the default case
    case_writes = op.attrs['case_writes']
    case_envs = []
    for bidx in sub_blocks:
        e = dict(env)
        run_block(prog.block(bidx), e, ctx)
        case_envs.append(e)
    has_default = '' in cond_names
    for out_var in op.outputs['Out']:
        n = out_var.name
        val = env.get(n)
        if val is None and not (has_default and
                                n in case_writes[cond_names.index('')]):
            # No prior value and no default writing it: when every condition
            # is false the var would be undefined — the reference's runtime
            # error, surfaced here at trace time.
            raise ValueError(
                "Switch: %r is only written in conditional cases and has no "
                "prior value or default-case write to fall back to" % n)
        # Fold cases in reverse: the first true condition wins, default (last
        # declared) is the base.
        for cn, writes, e in reversed(list(zip(cond_names, case_writes,
                                               case_envs))):
            if n not in writes:
                continue
            if cn == '':
                val = e[n]
            else:
                c = data_of(env[cn])
                val = _pred_where(c, e[n], val)
        env[n] = val


# ---------------------------------------------------------------------------
# static_rnn  (scan over leading/time axis)
# ---------------------------------------------------------------------------

@register_block_op('static_rnn')
def _static_rnn(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    step_ins = op.attrs['step_ins']     # [(outer, inner)]
    mems = op.attrs['mems']             # [{'pre','init','upd'}]
    outs = op.attrs['outs']             # [(inner, outer)]

    xs = tuple(data_of(env[o]) for o, _ in step_ins)
    init = tuple(env[m['init']] for m in mems)
    outer = dict(env)
    T = xs[0].shape[0]

    def body(carry, t_xs):
        t, xt = t_xs
        e = dict(outer)
        for (_, inner), x in zip(step_ins, xt):
            e[inner] = x
        for m, c in zip(mems, carry):
            e[m['pre']] = c
        run_block(sub, e, _iter_ctx(ctx, t))
        new = tuple(e[m['upd']] for m in mems)
        ys = tuple(data_of(e[inner]) for inner, _ in outs)
        return new, ys

    _, ys = lax.scan(body, init, (jnp.arange(T), xs))
    for (inner, outer_name), y in zip(outs, ys):
        env[outer_name] = y


# ---------------------------------------------------------------------------
# dynamic_rnn  (scan over padded [batch, T, ...] with length masking)
# ---------------------------------------------------------------------------

@register_block_op('dynamic_rnn')
def _dynamic_rnn(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    step_ins = op.attrs['step_ins']
    static_ins = op.attrs['static_ins']
    mems = op.attrs['mems']             # [{'pre','init','value','shape','upd'}]
    outs = op.attrs['outs']

    seq0 = env[step_ins[0][0]]
    if not isinstance(seq0, SeqValue):
        raise ValueError("DynamicRNN.step_input expects a lod_level>0 "
                         "sequence var (padded dense + lengths)")
    lengths = seq0.lengths
    B, T = seq0.data.shape[0], seq0.data.shape[1]

    def seq_steps(o):
        v = env[o]
        d = data_of(v)
        return jnp.moveaxis(d, 1, 0)    # [T, B, ...]

    xs = tuple(seq_steps(o) for o, _ in step_ins)
    init = []
    for m in mems:
        if m.get('init'):
            init.append(data_of(env[m['init']]))
        else:
            shape = (B,) + tuple(m.get('shape') or ())
            import numpy as np
            dt = m.get('dtype', 'float32')
            init.append(jnp.full(shape, float(m.get('value', 0.0)),
                                 np.dtype(dt) if dt != 'bfloat16'
                                 else jnp.bfloat16))
    init = tuple(init)
    outer = dict(env)

    def body(carry, t_xs):
        t, xt = t_xs
        e = dict(outer)
        for (_, inner), x in zip(step_ins, xt):
            e[inner] = x
        for o, inner in static_ins:
            e[inner] = outer[o]
        for m, c in zip(mems, carry):
            e[m['pre']] = c
        run_block(sub, e, _iter_ctx(ctx, t))
        active = (t < lengths)          # [B]
        new = tuple(_pred_where(active, data_of(e[m['upd']]), c)
                    for m, c in zip(mems, carry))
        ys = tuple(data_of(e[inner]) for inner, _ in outs)
        return new, ys

    _, ys = lax.scan(body, init, (jnp.arange(T), xs))
    for (inner, outer_name), y in zip(outs, ys):
        y = jnp.moveaxis(y, 0, 1)       # [B, T, ...]
        if jnp.issubdtype(y.dtype, jnp.floating):
            mask = (jnp.arange(T)[None, :] < lengths[:, None])
            y = y * mask.reshape(mask.shape + (1,) * (y.ndim - 2)).astype(y.dtype)
        env[outer_name] = SeqValue(y, lengths)
