"""Structured control flow: sub-block ops lowered to XLA control flow.

Parity: reference paddle/fluid/operators/{while_op.cc, conditional_block_op.cc,
recurrent_op.cc, array_write_op.cc (LoDTensorArray)} and the Python-side
layers/control_flow.py While/Switch/IfElse/StaticRNN/DynamicRNN.

TPU-first redesign: the reference interprets sub-blocks in fresh C++ scopes
(one scope per loop iteration, kept alive for the backward pass). Under XLA
everything is one traced computation, so:
  - `while`      -> lax.while_loop over an explicit carry dict (or a bounded
                    lax.scan with predicated updates when max_iters is given,
                    which keeps the loop differentiable);
  - `static_rnn` -> lax.scan over the leading (time) axis;
  - `dynamic_rnn`-> lax.scan over padded [batch, T, ...] sequences with
                    per-sequence length masking of memory updates;
  - `ifelse`/`switch` -> both branches execute, outputs merged by predicated
                    select (XLA's branch-free equivalent; cheap on TPU where
                    divergent control flow would stall the vector units).
LoDTensorArray becomes a fixed-capacity buffer + live length (ArrayValue),
making arrays legal loop carries.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import (register, register_block_op, run_block, data_of,
                        ArrayValue, SeqValue, Ctx, DEFAULT_ARRAY_CAPACITY)


def _scalar_bool(c):
    c = data_of(c)
    return jnp.reshape(c, (-1,))[0].astype(bool)


def _iter_ctx(ctx, t):
    """Fold the loop-iteration counter into the PRNG key so random ops
    (dropout etc.) inside loop bodies draw fresh bits every step."""
    return Ctx(jax.random.fold_in(ctx.key, t), is_test=ctx.is_test,
               amp=ctx.amp, platform=ctx.platform, mesh=ctx.mesh,
               manual_axes=ctx.manual_axes)


def _pred_where(cond, a, b):
    """Predicated merge with ndim alignment (cond may be [N,1] vs val [N,D])."""
    def one(x, y):
        c = cond
        while c.ndim > x.ndim:
            c = jnp.squeeze(c, -1)
        while c.ndim < x.ndim:
            c = c[..., None]
        return jnp.where(c, x, y)
    return jax.tree_util.tree_map(one, a, b)


# ---------------------------------------------------------------------------
# LoDTensorArray ops
# ---------------------------------------------------------------------------

@register('array_write')
def _array_write(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    i = jnp.reshape(data_of(ins['I'][0]), (-1,))[0].astype(jnp.int32)
    arrs = ins.get('Array', [])
    if arrs and isinstance(arrs[0], ArrayValue):
        arr = arrs[0]
        buf, length = arr.buffer, arr.length
    else:
        cap = int(attrs.get('capacity', DEFAULT_ARRAY_CAPACITY))
        buf = jnp.zeros((cap,) + tuple(x.shape), x.dtype)
        length = jnp.asarray(0, jnp.int32)
    # Writes past capacity clamp to the last slot (dynamic_update_index
    # semantics); length is clamped too so reads stay in range. Size the
    # array via create_array/array_write(capacity=) for longer loops.
    cap = buf.shape[0]
    lax.cond(i >= cap,
             lambda: jax.debug.print(
                 'WARNING: array_write index {i} >= capacity {c}; write '
                 'clamped to the last slot — pass capacity= to '
                 'create_array/array_write for longer loops', i=i, c=cap),
             lambda: None)
    buf = lax.dynamic_update_index_in_dim(buf, x.astype(buf.dtype), i, axis=0)
    length = jnp.minimum(jnp.maximum(length, i + 1), cap)
    return {'Out': ArrayValue(buf, length)}


@register('array_read')
def _array_read(ins, attrs, ctx):
    arr = ins['Array'][0]
    i = jnp.reshape(data_of(ins['I'][0]), (-1,))[0].astype(jnp.int32)
    return {'Out': lax.dynamic_index_in_dim(arr.buffer, i, axis=0,
                                            keepdims=False)}


@register('array_length')
def _array_length(ins, attrs, ctx):
    arr = ins['Array'][0]
    return {'Out': jnp.reshape(arr.length, (1,)).astype(jnp.int64)}


@register('array_stack')
def _array_stack(ins, attrs, ctx):
    """Materialize a LoDTensorArray as one [capacity, ...] stacked tensor
    (extension backing contrib's BeamSearchDecoder; the reference walks the
    LoDTensorArray on the host instead). Slots never written are zeros —
    size the array's capacity to the loop trip count."""
    arr = ins['Array'][0]
    return {'Out': arr.buffer}


# ---------------------------------------------------------------------------
# while
# ---------------------------------------------------------------------------

@register_block_op('while')
def _while(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    cond_name = op.inputs['Condition'][0].name
    carry_names = [v.name for v in op.outputs['Out']]
    missing = [n for n in carry_names if n not in env]
    if missing:
        raise ValueError(
            "While: loop-carried vars %s must be written (e.g. array_write / "
            "fill_constant) before the loop so their shapes are known" % missing)

    outer = dict(env)
    ITER = '__while_iter__'
    init = {n: env[n] for n in carry_names}
    init[ITER] = jnp.asarray(0, jnp.int32)

    def get_cond(carry):
        # The body may not update cond (bounded loops); fall back to the
        # loop-invariant outer value then.
        return carry[cond_name] if cond_name in carry else outer[cond_name]

    def body_env(carry):
        t = carry[ITER]
        e = dict(outer)
        e.update({n: carry[n] for n in carry_names})
        run_block(sub, e, _iter_ctx(ctx, t))
        new = {n: e[n] for n in carry_names}
        new[ITER] = t + 1
        return new

    max_iters = op.attrs.get('max_iters')
    if max_iters:
        # Differentiable bounded form: run max_iters steps, predicate every
        # update on the (pre-step) condition. Grad flows via lax.scan.
        def step(carry, _):
            alive = _scalar_bool(get_cond(carry))
            new = body_env(carry)
            merged = {n: _pred_where(alive, new[n], carry[n])
                      for n in carry_names}
            merged[ITER] = new[ITER]
            return merged, None
        final, _ = lax.scan(step, init, None, length=int(max_iters))
    else:
        final = lax.while_loop(
            lambda c: _scalar_bool(get_cond(c)), body_env, init)
    final.pop(ITER)
    env.update(final)


# ---------------------------------------------------------------------------
# ifelse / switch  (predicated select)
# ---------------------------------------------------------------------------

@register_block_op('ifelse')
def _ifelse(op, env, ctx):
    """Both branches execute; outputs merge via predicated select.

    Gradient hazard (standard JAX where-pitfall): if the UNTAKEN branch
    computes NaN/Inf from inputs the condition was guarding (log/sqrt/div),
    the 0*NaN in its cotangent poisons gradients of shared inputs even
    though the forward value is discarded. Clamp the guarded input inside
    the branch (the double-where trick, e.g. log(where(cond, x, 1.0)))
    so the untaken side stays finite; the reference runs only the taken
    branch and never hits this.
    """
    prog = op.block.program
    t_idx, f_idx = op.attrs['sub_blocks']
    cond = data_of(env[op.inputs['Cond'][0].name])
    te = dict(env)
    run_block(prog.block(t_idx), te, ctx)
    fe = dict(env)
    run_block(prog.block(f_idx), fe, ctx)
    for out_var, tn, fn in zip(op.outputs['Out'], op.attrs['true_outs'],
                               op.attrs['false_outs']):
        env[out_var.name] = _pred_where(cond, data_of(te[tn]),
                                        data_of(fe[fn]))
    # Branch writes to outer-scope vars (e.g. assign(output=outer)) merge
    # too, same as Switch; a var untouched by a branch keeps its pre-if
    # value on that side.
    for v in op.outputs.get('OuterOut', []):
        n = v.name
        env[n] = _pred_where(cond, data_of(te.get(n, env[n])),
                             data_of(fe.get(n, env[n])))


@register_block_op('switch')
def _switch(op, env, ctx):
    prog = op.block.program
    sub_blocks = op.attrs['sub_blocks']
    cond_names = op.attrs['cond_names']   # '' marks the default case
    case_writes = op.attrs['case_writes']
    case_envs = []
    for bidx in sub_blocks:
        e = dict(env)
        run_block(prog.block(bidx), e, ctx)
        case_envs.append(e)
    has_default = '' in cond_names
    for out_var in op.outputs['Out']:
        n = out_var.name
        val = env.get(n)
        if val is None and not (has_default and
                                n in case_writes[cond_names.index('')]):
            # No prior value and no default writing it: when every condition
            # is false the var would be undefined — the reference's runtime
            # error, surfaced here at trace time.
            raise ValueError(
                "Switch: %r is only written in conditional cases and has no "
                "prior value or default-case write to fall back to" % n)
        # Fold cases in reverse: the first true condition wins, default (last
        # declared) is the base.
        for cn, writes, e in reversed(list(zip(cond_names, case_writes,
                                               case_envs))):
            if n not in writes:
                continue
            if cn == '':
                val = e[n]
            else:
                c = data_of(env[cn])
                val = _pred_where(c, e[n], val)
        env[n] = val


# ---------------------------------------------------------------------------
# static_rnn  (scan over leading/time axis)
# ---------------------------------------------------------------------------

@register_block_op('static_rnn')
def _static_rnn(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    step_ins = op.attrs['step_ins']     # [(outer, inner)]
    mems = op.attrs['mems']             # [{'pre','init','upd'}]
    outs = op.attrs['outs']             # [(inner, outer)]

    xs = tuple(data_of(env[o]) for o, _ in step_ins)
    init = tuple(env[m['init']] for m in mems)
    outer = dict(env)
    T = xs[0].shape[0]

    def body(carry, t_xs):
        t, xt = t_xs
        e = dict(outer)
        for (_, inner), x in zip(step_ins, xt):
            e[inner] = x
        for m, c in zip(mems, carry):
            e[m['pre']] = c
        run_block(sub, e, _iter_ctx(ctx, t))
        new = tuple(e[m['upd']] for m in mems)
        ys = tuple(data_of(e[inner]) for inner, _ in outs)
        return new, ys

    _, ys = lax.scan(body, init, (jnp.arange(T), xs))
    for (inner, outer_name), y in zip(outs, ys):
        env[outer_name] = y


# ---------------------------------------------------------------------------
# dynamic_rnn  (scan over padded [batch, T, ...] with length masking)
# ---------------------------------------------------------------------------

@register_block_op('dynamic_rnn')
def _dynamic_rnn(op, env, ctx):
    prog = op.block.program
    sub = prog.block(op.attrs['sub_block'])
    step_ins = op.attrs['step_ins']
    static_ins = op.attrs['static_ins']
    mems = op.attrs['mems']             # [{'pre','init','value','shape','upd'}]
    outs = op.attrs['outs']

    seq0 = env[step_ins[0][0]]
    if not isinstance(seq0, SeqValue):
        raise ValueError("DynamicRNN.step_input expects a lod_level>0 "
                         "sequence var (padded dense + lengths)")
    lengths = seq0.lengths
    B, T = seq0.data.shape[0], seq0.data.shape[1]

    def seq_steps(o):
        v = env[o]
        d = data_of(v)
        return jnp.moveaxis(d, 1, 0)    # [T, B, ...]

    xs = tuple(seq_steps(o) for o, _ in step_ins)
    init = []
    for m in mems:
        if m.get('init'):
            init.append(data_of(env[m['init']]))
        else:
            shape = (B,) + tuple(m.get('shape') or ())
            import numpy as np
            dt = m.get('dtype', 'float32')
            init.append(jnp.full(shape, float(m.get('value', 0.0)),
                                 np.dtype(dt) if dt != 'bfloat16'
                                 else jnp.bfloat16))
    init = tuple(init)
    outer = dict(env)

    def body(carry, t_xs):
        t, xt = t_xs
        e = dict(outer)
        for (_, inner), x in zip(step_ins, xt):
            e[inner] = x
        for o, inner in static_ins:
            e[inner] = outer[o]
        for m, c in zip(mems, carry):
            e[m['pre']] = c
        run_block(sub, e, _iter_ctx(ctx, t))
        active = (t < lengths)          # [B]
        new = tuple(_pred_where(active, data_of(e[m['upd']]), c)
                    for m, c in zip(mems, carry))
        ys = tuple(data_of(e[inner]) for inner, _ in outs)
        return new, ys

    _, ys = lax.scan(body, init, (jnp.arange(T), xs))
    for (inner, outer_name), y in zip(outs, ys):
        y = jnp.moveaxis(y, 0, 1)       # [B, T, ...]
        if jnp.issubdtype(y.dtype, jnp.floating):
            mask = (jnp.arange(T)[None, :] < lengths[:, None])
            y = y * mask.reshape(mask.shape + (1,) * (y.ndim - 2)).astype(y.dtype)
        env[outer_name] = SeqValue(y, lengths)
