"""Mixture-of-experts op lowering.

TPU-first extension (no reference counterpart — the reference predates MoE
layers; closest ancestor is its conditional-computation machinery,
fluid/layers/control_flow.py Switch). The `moe_mlp` op is a top-k gated
two-layer expert FFN:

  gate_logits = x @ gate_w                       [N, E]
  expert e:  y = act(x @ w1[e] + b1[e]) @ w2[e] + b2[e]

Dispatch uses the Switch/GShard fixed-capacity packing semantics of
paddle_tpu.parallel.moe: tokens are routed top-k (k=1 Switch raw-prob
gates, k>1 GShard renormalized gates), packed into [E, capacity] slots
(overflow dropped, first choices before second — static shapes for XLA),
gate-weighted on return. The op also emits the Switch/GShard
load-balancing auxiliary loss (E * sum_e f_e * P_e) as a scalar `AuxLoss`
output for the model to add to its objective. Two execution paths, same
math:

- mesh path: when the step is compiled against a mesh (DistributeTranspiler
  or ParallelExecutor) whose dp axis size divides num_experts, experts are
  sharded num_experts/dp-per-device over dp and tokens ride TWO
  all_to_alls (parallel/moe.py moe_apply) — true expert parallelism on
  the ICI.
- dense path: identical pack/transform/unpack with the experts vmapped
  locally (single device, or expert count not a multiple of mesh size).

The two paths agree exactly when capacity is not exceeded; under overflow
the drop PATTERN differs (per-shard vs global cumsum order) — the standard
TPU MoE trade, tested in tests/test_pipeline_moe.py.
"""
import jax
import jax.numpy as jnp

from ..lowering import register, data_of, amp_cast

_ACTS = {
    'relu': jax.nn.relu,
    'gelu': jax.nn.gelu,
    'tanh': jnp.tanh,
    'sigmoid': jax.nn.sigmoid,
    'swish': jax.nn.silu,
    None: lambda x: x,
    '': lambda x: x,
}


def supported_acts():
    """Expert activations the rule can lower (layers.moe_mlp validates
    against this at construction time)."""
    return set(_ACTS)


def _expert_mlp(p, t, act):
    h = _ACTS[act](t @ p['w1'] + p['b1'])
    return h @ p['w2'] + p['b2']


def _dense_moe(params, x, logits, capacity_factor, act, top_k):
    """Local pack/transform/unpack with the same fixed-capacity semantics
    as parallel.moe.moe_apply (minus the all_to_all exchanges) — routing
    math is shared via pack_topk/combine_topk so the paths cannot drift."""
    from ...parallel.moe import pack_topk, combine_topk
    nt = x.shape[0]
    n_exp = logits.shape[-1]
    cap = int(max(1, capacity_factor * top_k * nt / n_exp))
    send, route = pack_topk(x, logits, n_exp, cap, top_k)
    out = jax.vmap(lambda p, t: _expert_mlp(p, t, act))(params, send)
    return combine_topk(out, route, x.dtype)


@register('moe_mlp')
def _moe_mlp(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    gate_w = data_of(ins['GateW'][0])
    params = {
        'w1': data_of(ins['W1'][0]), 'b1': data_of(ins['B1'][0]),
        'w2': data_of(ins['W2'][0]), 'b2': data_of(ins['B2'][0]),
    }
    act = attrs.get('act') or None
    cf = float(attrs.get('capacity_factor', 2.0))
    n_exp = int(attrs.get('num_experts'))
    top_k = int(attrs.get('top_k', 1))

    shape_in = x.shape
    if x.ndim > 2:
        x = x.reshape(-1, x.shape[-1])
    x, gate_w = amp_cast(ctx, x, gate_w)
    params = dict(zip(params, amp_cast(ctx, *params.values())))
    logits = (x @ gate_w).astype(jnp.float32)

    from ...parallel.moe import load_balancing_loss
    aux = load_balancing_loss(logits, top_k)

    mesh = ctx.mesh
    if (mesh is not None and 'dp' in getattr(mesh, 'shape', {})
            and n_exp % mesh.shape['dp'] == 0):
        from ...parallel.moe import moe_apply
        from jax.sharding import NamedSharding, PartitionSpec as P
        # experts block-sharded over dp (n_exp/dp per device); tokens
        # already batch-sharded over dp
        params = jax.tree_util.tree_map(
            lambda p: jax.lax.with_sharding_constraint(
                p, NamedSharding(mesh, P('dp'))), params)
        y = moe_apply(lambda p, t: _expert_mlp(p, t, act), params, x,
                      logits, mesh, axis='dp', capacity_factor=cf,
                      top_k=top_k)
    else:
        y = _dense_moe(params, x, logits, cf, act, top_k)
    return {'Out': y.reshape(shape_in[:-1] + y.shape[-1:]),
            'AuxLoss': aux}
