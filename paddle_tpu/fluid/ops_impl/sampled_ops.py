"""Sampled-softmax-family and beam-search rules.

Parity: reference paddle/fluid/operators/{nce,hierarchical_sigmoid,
beam_search,beam_search_decode}_op.* — the reference implements these as
host-side loops over LoD structures (NCE sampling with a CPU sampler,
hsigmoid via MatrixBitCodeFunctor, beam search via LoD pruning).

TPU-first: NCE samples negatives with the step PRNG and evaluates one
batched [B, k+T] gather-matmul (MXU); hsigmoid turns the complete-binary-
tree path walk into a static [B, max_depth] gather + masked BCE; beam
search is a dense [batch, beam] top-k with explicit parent pointers
(replacing LoD lineage), so the whole decode loop stays on device.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import register, data_of, like, SeqValue, use_kernel


@register('nce')
def _nce(ins, attrs, ctx):
    """Noise-contrastive estimation with a uniform noise distribution
    (reference nce_op.h defaults): binary logistic loss on the true class
    vs num_neg sampled classes, logits corrected by log(k*q)."""
    x = data_of(ins['Input'][0])                         # [B, D]
    label = data_of(ins['Label'][0]).astype(jnp.int32)   # [B, T]
    if label.ndim == 1:
        label = label[:, None]
    w = data_of(ins['Weight'][0])                        # [N, D]
    b = data_of(ins['Bias'][0]) if ins.get('Bias') else None   # [N, 1]
    N = int(attrs['num_total_classes'])
    k = int(attrs.get('num_neg_samples', 10))
    B, T = label.shape

    neg = jax.random.randint(ctx.rng(), (k,), 0, N)      # shared noise draw
    log_kq = jnp.log(jnp.asarray(k / N, x.dtype))

    def logits_for(idx_2d):
        wr = jnp.take(w, idx_2d, axis=0)                 # [..., D]
        out = jnp.einsum('bd,b...d->b...', x, wr)
        if b is not None:
            out = out + jnp.take(b[:, 0], idx_2d)
        return out

    true_logit = logits_for(label) - log_kq              # [B, T]
    neg_logit = logits_for(jnp.broadcast_to(neg[None, :], (B, k))) - log_kq

    pos_loss = jnp.sum(jax.nn.softplus(-true_logit), axis=1)
    neg_loss = jnp.sum(jax.nn.softplus(neg_logit), axis=1)
    cost = (pos_loss + neg_loss)[:, None]
    if ins.get('SampleWeight'):
        cost = cost * data_of(ins['SampleWeight'][0]).reshape(B, 1)
    return {'Cost': cost,
            'SampleLogits': jnp.concatenate([true_logit, neg_logit], axis=1),
            'SampleLabels': jnp.concatenate(
                [label, jnp.broadcast_to(neg[None, :], (B, k))],
                axis=1).astype(jnp.int64)}


@register('hierarchical_sigmoid')
def _hsigmoid(ins, attrs, ctx):
    """Complete-binary-tree hierarchical sigmoid (reference
    hierarchical_sigmoid_op.h SimpleCode): leaf for class c is heap node
    c + num_classes; the root->leaf internal nodes and branch bits come
    from the binary representation, evaluated as one masked gather."""
    x = data_of(ins['X'][0])                             # [B, D]
    w = data_of(ins['W'][0])                             # [num_classes-1, D]
    label = data_of(ins['Label'][0]).astype(jnp.int32)
    if label.ndim > 1:
        label = label.reshape(label.shape[0])
    bias = data_of(ins['Bias'][0]) if ins.get('Bias') else None
    C = int(attrs['num_classes'])
    B = x.shape[0]
    max_len = max(1, int(np.ceil(np.log2(C))))

    code = label + C                                     # heap leaf id
    # path length = floor(log2(code)); static loop over max depth
    length = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
    j = jnp.arange(max_len)[None, :]                     # [1, L]
    valid = j < length[:, None]
    shift = jnp.maximum(length[:, None] - j, 1)
    anc = jnp.right_shift(code[:, None], shift)          # ancestor heap ids
    bit = jnp.right_shift(code[:, None], shift - 1) & 1
    idx = jnp.clip(anc - 1, 0, C - 2)                    # weight row

    wr = jnp.take(w, idx, axis=0)                        # [B, L, D]
    pre = jnp.einsum('bd,bld->bl', x, wr)
    if bias is not None:
        pre = pre + jnp.take(bias.reshape(-1), idx)
    pre = jnp.clip(pre, -40.0, 40.0)
    # BCE with logits, target = bit
    loss = jax.nn.softplus(pre) - bit * pre
    out = jnp.sum(jnp.where(valid, loss, 0.0), axis=1, keepdims=True)
    return {'Out': out, 'PreOut': pre}


@register('beam_search')
def _beam_search(ins, attrs, ctx):
    """One beam step on dense [batch*beam, K] candidates: joint top-k over
    beam*K per source, with explicit parent pointers instead of the
    reference's LoD lineage. Finished beams (pre_id == end_id) contribute a
    single end_id candidate carrying their accumulated score forward.

    When the inputs are capacity-form 2-level SeqValues — the book's
    While-loop LoD decoder running verbatim — the step instead follows the
    reference beam_search_op.cc algorithm exactly (ops_impl/lod_beam.py)."""
    from ..lowering import SeqValue
    from .lod_beam import normalize_capacity, beam_search_step
    psc = ins['pre_scores'][0] if ins.get('pre_scores') else None
    if isinstance(psc, SeqValue) and psc.outer_lengths:
        p_ids, p_sc, cids, csc = normalize_capacity(
            ins['pre_ids'][0], psc, ins['ids'][0], ins['scores'][0],
            int(attrs['beam_size']))
        sel_ids, sel_scores, parents = beam_search_step(
            p_ids, p_sc, cids, csc, int(attrs['beam_size']),
            int(attrs['end_id']))
        return {'selected_ids': sel_ids, 'selected_scores': sel_scores,
                'parent_idx': parents.astype(jnp.int64)}
    pre_ids = data_of(ins['pre_ids'][0]).astype(jnp.int32)   # [B*b, 1]
    ids = data_of(ins['ids'][0]).astype(jnp.int32)           # [B*b, K]
    scores = data_of(ins['scores'][0]).astype(jnp.float32)   # [B*b, K]
    beam = int(attrs['beam_size'])
    end_id = int(attrs['end_id'])
    Bb, K = ids.shape
    B = Bb // beam

    finished = (pre_ids[:, 0] == end_id)                 # [B*b]
    if not ins.get('pre_scores'):
        raise ValueError(
            "beam_search requires pre_scores (the previous step's "
            "selected_scores) to carry finished beams' scores forward")
    keep_score = data_of(ins['pre_scores'][0]).astype(jnp.float32).reshape(Bb)
    # finished: only candidate 0 is live (end_id, score carried unchanged)
    cand_scores = jnp.where(
        finished[:, None],
        jnp.where(jnp.arange(K)[None, :] == 0,
                  keep_score[:, None], -jnp.inf),
        scores)
    cand_ids = jnp.where(finished[:, None], end_id, ids)

    flat_scores = cand_scores.reshape(B, beam * K)
    top_scores, top_pos = lax.top_k(flat_scores, beam)   # [B, beam]
    # global flat row index into [B*beam]: directly gatherable for
    # dense beam-state reordering (contrib BeamSearchDecoder)
    parent = top_pos // K + jnp.arange(B)[:, None] * beam
    sel_ids = jnp.take_along_axis(cand_ids.reshape(B, beam * K), top_pos,
                                  axis=1)
    return {'selected_ids': sel_ids.reshape(Bb, 1).astype(jnp.int64),
            'selected_scores': top_scores.reshape(Bb, 1),
            'parent_idx': parent.reshape(Bb).astype(jnp.int64)}


@register('attention_lstm_beam_decode')
def _attention_lstm_beam_decode(ins, attrs, ctx):
    """Whole beam-search generation as ONE lax.scan (TPU-first fusion of the
    reference's While-loop decoder in book test_machine_translation.py:
    decode()): embed -> attend -> LSTM cell -> project -> joint top-k ->
    reorder beams, all inside a single XLA while loop. Weights match the
    training-time `attention_lstm_decoder` op, so a trained model decodes
    with no re-plumbing.

    Inputs: EncOut [B,S,D] (SeqValue), WDec [E+D,4H], UDec [H,4H],
    BDec [1,4H], WAttnQ [H,D], WEmb [V,E], WOut [H,V], BOut [1,V].
    Attrs: beam_size, max_len, start_id, end_id.
    Outputs: SentenceIds [B, beam, max_len], SentenceScores [B, beam]."""
    enc = ins['EncOut'][0]
    enc_data = data_of(enc)                              # [B, S, D]
    if isinstance(enc, SeqValue):
        enc_mask = enc.mask(jnp.float32)
    else:
        enc_mask = jnp.ones(enc_data.shape[:2], jnp.float32)
    w_dec = data_of(ins['WDec'][0])
    u_dec = data_of(ins['UDec'][0])
    b_dec = data_of(ins['BDec'][0]) if ins.get('BDec') else 0.0
    w_q = data_of(ins['WAttnQ'][0])
    w_emb = data_of(ins['WEmb'][0])
    w_out = data_of(ins['WOut'][0])
    b_out = data_of(ins['BOut'][0]) if ins.get('BOut') else 0.0

    beam = int(attrs['beam_size'])
    max_len = int(attrs['max_len'])
    start_id = int(attrs.get('start_id', 0))
    end_id = int(attrs['end_id'])
    B, S, D = enc_data.shape
    H = u_dec.shape[0]

    enc_t = jnp.repeat(enc_data, beam, axis=0)           # [Bb, S, D]
    mask_t = jnp.repeat(enc_mask, beam, axis=0)
    params = (w_dec, u_dec, b_dec, w_q, w_emb, w_out, b_out)

    # the scan body IS the step-form decode (lod_beam.attention_beam_step)
    # the continuous-batching engine drives slot by slot — one definition,
    # so serving/decode.py's per-step path and this fused whole-sequence
    # scan are fetch-equivalent by construction
    from .lod_beam import attention_beam_step, beam_init_carry

    def step(carry, _):
        return attention_beam_step(params, enc_t, mask_t, carry, beam,
                                   end_id)

    (_, _, _, accN, _), (ids_seq, par_seq, sc_seq) = lax.scan(
        step, beam_init_carry(B, beam, H, start_id, enc_data.dtype),
        None, length=max_len)

    def back(beam_ptr, xs):
        ids_t, par_t = xs                                 # [B, beam]
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)
        return jnp.take_along_axis(par_t, beam_ptr, axis=1), tok

    init = jnp.broadcast_to(jnp.arange(beam)[None, :], (B, beam))
    _, toks_rev = lax.scan(back, init,
                           (jnp.flip(ids_seq, 0), jnp.flip(par_seq, 0)))
    sent = jnp.flip(jnp.transpose(toks_rev, (1, 2, 0)), -1)
    return {'SentenceIds': sent.astype(jnp.int64),
            'SentenceScores': accN.reshape(B, beam)}


@register('attention_lstm_beam_decode_step')
def _attention_lstm_beam_decode_step(ins, attrs, ctx):
    """A BUNDLE of decode steps (attr `bundle`, default 1) over a fixed
    pool of independent SLOTS — the step-form factoring of
    `attention_lstm_beam_decode`'s scan body that the continuous-batching
    engine (paddle_tpu.serving.decode) drives: sequences join/leave
    between dispatches on the host while this op advances every ACTIVE
    slot's beam state in place. bundle>1 runs that many steps inside one
    XLA module (the PR 4 K-step-bundling move applied to decode: per-call
    dispatch/sync cost is paid once per bundle, not once per token);
    slots that finish mid-bundle freeze in-graph — their state, history
    and step count stop advancing — so results are bit-identical to
    bundle=1, only the host's release granularity coarsens.

    State inputs (all persistable; written ones re-emitted under *Out so
    the memory plan donates them — in-place HBM updates per step):
      H, C        [slots, beam, hidden]   LSTM carry
      PrevIds     [slots, beam] int32     last selected token per beam
      Acc         [slots, beam] float32   accumulated log-probs
      Fin         [slots, beam] bool      beam emitted end_id
      IdsHist     [slots, max_len, beam]  int32 emitted tokens per step
      ParHist     [slots, max_len, beam]  int32 parent pointers per step
      Step        [slots] int32           steps taken by the occupant
      Active      [slots] bool            slot occupied and decoding
    Read-only state (not written, so not donated — no per-step copy):
      Enc [slots, src_cap, D], Mask [slots, src_cap],
      Limit [slots] int32 (per-request max decode length <= max_len).
    Weights: same tensors as attention_lstm_beam_decode.

    Outputs additionally expose Done [slots] (slot finished within THIS
    bundle: all beams ended, its per-request limit hit, or poisoned) and
    Bad [slots] (NaN detected in the slot's new scores — the
    anomaly-guard where-select pattern: every state update is masked by
    Active, so a dead or poisoned slot never perturbs a live one, and a
    poisoned slot is released alone).
    """
    from .lod_beam import attention_beam_step

    h = data_of(ins['H'][0])
    c = data_of(ins['C'][0])
    prev_ids = data_of(ins['PrevIds'][0]).astype(jnp.int32)
    acc = data_of(ins['Acc'][0]).astype(jnp.float32)
    fin = data_of(ins['Fin'][0]).astype(bool)
    enc = data_of(ins['Enc'][0])
    mask = data_of(ins['Mask'][0])
    ids_hist = data_of(ins['IdsHist'][0]).astype(jnp.int32)
    par_hist = data_of(ins['ParHist'][0]).astype(jnp.int32)
    step = data_of(ins['Step'][0]).astype(jnp.int32)
    limit = data_of(ins['Limit'][0]).astype(jnp.int32)
    active_in = data_of(ins['Active'][0]).astype(bool)
    params = (data_of(ins['WDec'][0]), data_of(ins['UDec'][0]),
              data_of(ins['BDec'][0]) if ins.get('BDec') else 0.0,
              data_of(ins['WAttnQ'][0]), data_of(ins['WEmb'][0]),
              data_of(ins['WOut'][0]),
              data_of(ins['BOut'][0]) if ins.get('BOut') else 0.0)

    slots, beam = prev_ids.shape
    t_cap = ids_hist.shape[1]
    end_id = int(attrs['end_id'])
    bundle = int(attrs.get('bundle', 1))

    enc_t = jnp.repeat(enc, beam, axis=0)            # [slots*beam, S, D]
    mask_t = jnp.repeat(mask, beam, axis=0)
    flat = lambda a: a.reshape((slots * beam,) + a.shape[2:])
    unflat = lambda a: a.reshape((slots, beam) + a.shape[1:])

    def one_step(carry, _):
        h, c, prev, acc, fin, ids_h, par_h, step, active, bad_acc = carry
        (h2, c2, ids2, acc2, fin2), (sel_ids, parent) = \
            _masked_beam_advance(params, enc_t, mask_t,
                                 (h, c, prev, acc, fin), active, beam,
                                 end_id)

        # per-slot history write at each slot's OWN step index
        at_t = ((jnp.arange(t_cap)[None, :] == step[:, None])
                & active[:, None])                   # [slots, t_cap]
        ids_h2 = jnp.where(at_t[:, :, None], sel_ids[:, None, :], ids_h)
        par_h2 = jnp.where(at_t[:, :, None], parent[:, None, :], par_h)
        step2 = step + active.astype(jnp.int32)

        acc_s = unflat(acc2)
        fin_s = unflat(fin2)
        bad_t = active & jnp.isnan(acc_s).any(axis=1)
        done_t = active & (fin_s.all(axis=1) | (step2 >= limit) | bad_t)
        return (h2, c2, ids2, acc2, fin2, ids_h2, par_h2, step2,
                active & ~done_t, bad_acc | bad_t), None

    carry0 = (flat(h), flat(c), flat(prev_ids), flat(acc), flat(fin),
              ids_hist, par_hist, step, active_in,
              jnp.zeros((slots,), bool))
    if bundle == 1:
        carry, _ = one_step(carry0, None)
    else:
        carry, _ = lax.scan(one_step, carry0, None, length=bundle)
    (h2, c2, ids2, acc2, fin2, ids_hist2, par_hist2, step2, active2,
     bad) = carry

    return {'HOut': unflat(h2), 'COut': unflat(c2),
            'PrevIdsOut': unflat(ids2), 'AccOut': unflat(acc2),
            'FinOut': unflat(fin2), 'IdsHistOut': ids_hist2,
            'ParHistOut': par_hist2, 'StepOut': step2,
            'ActiveOut': active2, 'Done': active_in & ~active2,
            'Bad': bad}


def _masked_beam_advance(params, enc_t, mask_t, carry5, active, beam,
                         end_id, attend=None):
    """One beam step over the slot pool with where-select masking (the
    anomaly guard's rollback pattern): only ACTIVE slots advance;
    everything else keeps its old state bit for bit, so joins/leaves
    between dispatches — and slots that finished earlier in a bundle —
    never disturb live ones. Shared by the dense and the paged step op
    so the two are bit-exact by construction. `attend` passes the paged
    op's fused-kernel attention through (lod_beam.attention_beam_step)."""
    from .lod_beam import attention_beam_step
    h, c, prev, acc, fin = carry5
    new_carry, (sel_ids, parent, _top) = attention_beam_step(
        params, enc_t, mask_t, carry5, beam, end_id, attend=attend)
    act_row = jnp.repeat(active, beam)               # [slots*beam]
    sel = lambda new, old: jnp.where(
        act_row.reshape((-1,) + (1,) * (new.ndim - 1)), new, old)
    return (sel(new_carry[0], h), sel(new_carry[1], c),
            sel(new_carry[2], prev), sel(new_carry[3], acc),
            sel(new_carry[4], fin)), (sel_ids, parent)


def _decode_weight_params(ins, prefix=''):
    """The WEIGHT_KEYS tuple from op inputs (prefix='Draft' pulls the
    draft model's tensors in the speculative step)."""
    return (data_of(ins[prefix + 'WDec'][0]),
            data_of(ins[prefix + 'UDec'][0]),
            data_of(ins[prefix + 'BDec'][0])
            if ins.get(prefix + 'BDec') else 0.0,
            data_of(ins[prefix + 'WAttnQ'][0]),
            data_of(ins[prefix + 'WEmb'][0]),
            data_of(ins[prefix + 'WOut'][0]),
            data_of(ins[prefix + 'BOut'][0])
            if ins.get(prefix + 'BOut') else 0.0)


def _gather_paged_enc(ins, src_cap):
    """Assemble per-slot encoder rows + attention mask from the page
    pools through the slot page tables — ONE in-graph gather per
    dispatch (amortized over the whole bundle), the PagedAttention
    lookup. Tail page-table entries point at the reserved ZERO page, so
    masked-out rows always read finite zeros."""
    pt_enc = data_of(ins['PtEnc'][0]).astype(jnp.int32)    # [C, NPE]
    enc_pages = data_of(ins['EncPages'][0])                # [Pe, ps, D]
    mask_pages = data_of(ins['MaskPages'][0])              # [Pe, ps]
    C, NPE = pt_enc.shape
    ps, D2 = enc_pages.shape[1], enc_pages.shape[2]
    enc = jnp.take(enc_pages, pt_enc, axis=0)              # [C,NPE,ps,D]
    enc = enc.reshape(C, NPE * ps, D2)[:, :src_cap]
    mask = jnp.take(mask_pages, pt_enc, axis=0).reshape(
        C, NPE * ps)[:, :src_cap]
    return enc, mask


def _paged_hist_write(pool, pt_hist, step, page_size, valid, rows,
                      n_pages):
    """Scatter one [slots, beam] history row into the page pool at each
    slot's own (page, offset): physical page = pt_hist[slot,
    step // page_size], offset = step % page_size. Invalid slots are
    redirected to the out-of-range page index and dropped — the page
    analogue of the dense op's where-select write."""
    lp = step // page_size                                 # [C] logical
    phys = jnp.take_along_axis(pt_hist, lp[:, None], axis=1)[:, 0]
    phys = jnp.where(valid, phys, n_pages)                 # drop
    off = step - lp * page_size
    return pool.at[phys, off].set(rows.astype(pool.dtype), mode='drop')


@register('attention_lstm_beam_paged_step')
def _attention_lstm_beam_paged_step(ins, attrs, ctx):
    """The paged-memory form of `attention_lstm_beam_decode_step`: the
    per-slot dense history/encoder buffers are replaced by fixed-size
    PAGES drawn from pool inputs, indexed through per-slot int32 page
    tables (serving/pages.py has the allocator; docs/serving.md the
    diagram). Shapes stay static: encoder rows are assembled by one
    in-graph gather per dispatch, history tokens scatter to
    (page_table[slot, step//page_size], step%page_size) with inactive
    rows dropped. The beam math, masking, bundling and Done/Bad
    semantics are the dense op's, shared code — the paged engine is
    bit-exact against the dense engine by construction
    (tests/test_decode.py's paged family drills it).

    State inputs (written -> donated): H, C, PrevIds, Acc, Fin, Step,
    Active as the dense op; HistIds/HistPar [pages, page_size, beam]
    are the token/parent history POOLS.
    Read-only: PtHist [slots, ceil(T/page_size)], PtEnc [slots,
    ceil(src_cap/page_size)] page tables (written at join time by the
    engine's scatter, constant during decode), EncPages [enc_pages,
    page_size, D], MaskPages [enc_pages, page_size], Limit.
    Attrs: beam_size, end_id, bundle, page_size, src_cap.
    """
    h = data_of(ins['H'][0])
    c = data_of(ins['C'][0])
    prev_ids = data_of(ins['PrevIds'][0]).astype(jnp.int32)
    acc = data_of(ins['Acc'][0]).astype(jnp.float32)
    fin = data_of(ins['Fin'][0]).astype(bool)
    step = data_of(ins['Step'][0]).astype(jnp.int32)
    limit = data_of(ins['Limit'][0]).astype(jnp.int32)
    active_in = data_of(ins['Active'][0]).astype(bool)
    pt_hist = data_of(ins['PtHist'][0]).astype(jnp.int32)
    hist_ids = data_of(ins['HistIds'][0])
    hist_par = data_of(ins['HistPar'][0])
    params = _decode_weight_params(ins)

    slots, beam = prev_ids.shape
    n_pages, page_size = hist_ids.shape[0], int(attrs['page_size'])
    end_id = int(attrs['end_id'])
    bundle = int(attrs.get('bundle', 1))
    src_cap = int(attrs['src_cap'])

    if use_kernel(ctx, 'paged_attention'):
        # fused path: the kernel reads the page POOLS through the page
        # table itself — the gathered [slots, S, D] buffer and its
        # per-beam repeat never materialize
        from ...ops.kernels import paged_attention
        pt_enc = data_of(ins['PtEnc'][0]).astype(jnp.int32)
        enc_pages = data_of(ins['EncPages'][0])
        mask_pages = data_of(ins['MaskPages'][0])
        enc_t = mask_t = None
        attend = lambda q: paged_attention(q, enc_pages, mask_pages,
                                           pt_enc, src_cap)
    else:
        attend = None
        enc, mask = _gather_paged_enc(ins, src_cap)
        enc_t = jnp.repeat(enc, beam, axis=0)        # [slots*beam, S, D]
        mask_t = jnp.repeat(mask, beam, axis=0)
    flat = lambda a: a.reshape((slots * beam,) + a.shape[2:])
    unflat = lambda a: a.reshape((slots, beam) + a.shape[1:])

    def one_step(carry, _):
        h, c, prev, acc, fin, ids_pool, par_pool, step, active, bad_acc \
            = carry
        (h2, c2, ids2, acc2, fin2), (sel_ids, parent) = \
            _masked_beam_advance(params, enc_t, mask_t,
                                 (h, c, prev, acc, fin), active, beam,
                                 end_id, attend=attend)
        ids_pool2 = _paged_hist_write(ids_pool, pt_hist, step, page_size,
                                      active, sel_ids, n_pages)
        par_pool2 = _paged_hist_write(par_pool, pt_hist, step, page_size,
                                      active, parent, n_pages)
        step2 = step + active.astype(jnp.int32)
        acc_s = unflat(acc2)
        fin_s = unflat(fin2)
        bad_t = active & jnp.isnan(acc_s).any(axis=1)
        done_t = active & (fin_s.all(axis=1) | (step2 >= limit) | bad_t)
        return (h2, c2, ids2, acc2, fin2, ids_pool2, par_pool2, step2,
                active & ~done_t, bad_acc | bad_t), None

    carry0 = (flat(h), flat(c), flat(prev_ids), flat(acc), flat(fin),
              hist_ids, hist_par, step, active_in,
              jnp.zeros((slots,), bool))
    if bundle == 1:
        carry, _ = one_step(carry0, None)
    else:
        carry, _ = lax.scan(one_step, carry0, None, length=bundle)
    (h2, c2, ids2, acc2, fin2, hist_ids2, hist_par2, step2, active2,
     bad) = carry

    return {'HOut': unflat(h2), 'COut': unflat(c2),
            'PrevIdsOut': unflat(ids2), 'AccOut': unflat(acc2),
            'FinOut': unflat(fin2), 'HistIdsOut': hist_ids2,
            'HistParOut': hist_par2, 'StepOut': step2,
            'ActiveOut': active2, 'Done': active_in & ~active2,
            'Bad': bad}


@register('attention_lstm_spec_decode_step')
def _attention_lstm_spec_decode_step(ins, attrs, ctx):
    """Speculative GREEDY decoding over the paged slot pool: a small
    DRAFT proposes spec_k tokens, the TARGET verifies them all in ONE
    dispatched module, accept/rollback entirely in-graph.

    Why it wins even for a recurrent target: the draft's proposals make
    every verify-step's INPUT token known up front, so the expensive
    position-independent work batches across all spec_k+1 positions —
    the embedding gather, the input half of the decoder matmul
    (x @ w_dec[:E]), and above all the [H, V] output projection +
    log-softmax/argmax run as ONE stacked matmul instead of one per
    step. Only the slim recurrence (attention query + ctx @ w_dec[E:] +
    h @ u_dec + cell) stays sequential. docs/serving.md carries the
    acceptance-rate math; the engine reports accept-rate from the
    Accepted output.

    Emission contract (token-exact vs greedy target-only decode, which
    is beam_size=1 through the paged step op): the emitted token at
    every position is the TARGET's own greedy argmax g_t; the draft
    only decides how many positions are valid. Position t is emitted
    iff every earlier proposal matched (d_s == g_s for s < t) and the
    slot is still within its limit and un-finished — so a slot emits
    between 1 and spec_k+1 tokens per dispatch (the +1 is the classic
    bonus token: verifying spec_k proposals yields spec_k+1 target
    distributions). Target, draft hidden state, and the next input
    token all roll back to the last VALID position in-graph
    (where-select gathers over the stacked per-position states).

    Draft forms (attr `draft`): 'weights' — a small attention-LSTM with
    its own Draft* weight inputs (same vocab + enc_dim as the target,
    any hidden/embedding size), state carried per slot in DraftH/DraftC;
    'table' — a [V] int32 next-token table input (DraftTable), the
    n-gram/prompt-lookup speculator: zero proposal cost, no state.

    State inputs as the paged beam op (beam dim fixed at 1) plus
    DraftH/DraftC [slots, draft_hidden] (weights draft only).
    Attrs: end_id, spec_k, page_size, src_cap, draft.
    Outputs additionally: Accepted [slots] int32 — draft proposals
    accepted this dispatch (emitted tokens minus the always-target
    correction/bonus token).
    """
    from .lod_beam import greedy_attend_cell

    h = data_of(ins['H'][0])[:, 0]                   # [C, Ht]
    c = data_of(ins['C'][0])[:, 0]
    prev = data_of(ins['PrevIds'][0]).astype(jnp.int32)[:, 0]
    acc = data_of(ins['Acc'][0]).astype(jnp.float32)[:, 0]
    fin = data_of(ins['Fin'][0]).astype(bool)[:, 0]
    step = data_of(ins['Step'][0]).astype(jnp.int32)
    limit = data_of(ins['Limit'][0]).astype(jnp.int32)
    active = data_of(ins['Active'][0]).astype(bool)
    pt_hist = data_of(ins['PtHist'][0]).astype(jnp.int32)
    hist_ids = data_of(ins['HistIds'][0])
    hist_par = data_of(ins['HistPar'][0])
    w_dec, u_dec, b_dec, w_q, w_emb, w_out, b_out = \
        _decode_weight_params(ins)

    C = prev.shape[0]
    n_pages, page_size = hist_ids.shape[0], int(attrs['page_size'])
    end_id = int(attrs['end_id'])
    spec_k = int(attrs['spec_k'])
    src_cap = int(attrs['src_cap'])
    R = spec_k + 1                       # verify steps = proposals + 1
    E = w_emb.shape[1]
    neg = jnp.finfo(jnp.float32).min

    if use_kernel(ctx, 'paged_attention'):
        # fused path (beam dim is 1 here): both the draft proposals and
        # the verify recurrence attend straight into the page pools
        from ...ops.kernels import paged_attention
        pt_enc = data_of(ins['PtEnc'][0]).astype(jnp.int32)
        enc_pages = data_of(ins['EncPages'][0])
        mask_pages = data_of(ins['MaskPages'][0])
        enc = mask = None
        attend = lambda q: paged_attention(q, enc_pages, mask_pages,
                                           pt_enc, src_cap)
    else:
        attend = None
        enc, mask = _gather_paged_enc(ins, src_cap)  # [C, S, D]

    # -- draft phase: propose spec_k tokens (and advance one past them,
    # so the draft state can roll back to any accepted position) -------
    if attrs.get('draft', 'weights') == 'table':
        table = data_of(ins['DraftTable'][0]).astype(jnp.int32)
        d_list, tok = [], prev
        for _ in range(R):
            tok = jnp.take(table, tok)
            d_list.append(tok)
        d_seq = jnp.stack(d_list)                    # [R, C]
        hd_seq = cd_seq = None
    else:
        dparams = _decode_weight_params(ins, prefix='Draft')
        h_d = data_of(ins['DraftH'][0])
        c_d = data_of(ins['DraftC'][0])

        def dstep(carry, _):
            hd, cd, tok = carry
            hd2, cd2, logits = greedy_attend_cell(dparams, enc, mask,
                                                  hd, cd, tok,
                                                  attend=attend)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (hd2, cd2, nxt), (nxt, hd2, cd2)

        _, (d_seq, hd_seq, cd_seq) = lax.scan(
            dstep, (h_d, c_d, prev), None, length=R)

    # -- verify phase: ONE bundled target pass over all R positions ----
    # batched (position-independent): embedding + input projection
    tok_in = jnp.concatenate([prev[None], d_seq[:R - 1]])    # [R, C]
    xw = jnp.take(w_emb, tok_in, axis=0) @ w_dec[:E] # [R, C, 4Ht]

    def vstep(carry, xw_t):
        h, c = carry
        q = h @ w_q
        if attend is not None:
            ctx_v = attend(q)
        else:
            scores = jnp.einsum('bd,bsd->bs', q, enc)
            scores = jnp.where(mask > 0, scores, neg)
            alpha = jax.nn.softmax(scores, axis=-1)
            ctx_v = jnp.einsum('bs,bsd->bd', alpha, enc)
        g = xw_t + ctx_v @ w_dec[E:] + h @ u_dec + b_dec
        gi, gf, gc, go = jnp.split(g, 4, axis=-1)
        c2 = jax.nn.sigmoid(gf) * c + jax.nn.sigmoid(gi) * jnp.tanh(gc)
        h2 = jax.nn.sigmoid(go) * jnp.tanh(c2)
        return (h2, c2), (h2, c2)

    _, (h_seq, c_seq) = lax.scan(vstep, (h, c), xw)  # [R, C, Ht]
    # batched: output projection + greedy choice over every position
    logp = jax.nn.log_softmax(
        (h_seq @ w_out + b_out).astype(jnp.float32), axis=-1)
    g_seq = jnp.argmax(logp, axis=-1).astype(jnp.int32)      # [R, C]
    lp_seq = jnp.take_along_axis(logp, g_seq[..., None],
                                 axis=-1)[..., 0]            # [R, C]

    # -- accept/rollback masking (all in-graph) ------------------------
    # position t (0-based) is emitted iff every earlier draft proposal
    # matched the target's own choice AND the slot is still live there
    match = g_seq[:R - 1] == d_seq[:R - 1]           # [R-1, C]
    valid = []
    v = active & ~fin & (step < limit)
    for t in range(R):
        if t > 0:
            v = (v & match[t - 1] & (g_seq[t - 1] != end_id)
                 & (step + t < limit))
        valid.append(v)
    valid = jnp.stack(valid)                         # [R, C] bool
    n_emit = valid.astype(jnp.int32).sum(axis=0)     # [C]
    accepted = (valid[:R - 1] & match).astype(jnp.int32).sum(axis=0)

    # history writes: each emitted token at its own (page, offset)
    ids_pool, par_pool = hist_ids, hist_par
    zero_par = jnp.zeros((C, 1), jnp.int32)          # beam 1: parent 0
    for t in range(R):
        ids_pool = _paged_hist_write(ids_pool, pt_hist, step + t,
                                     page_size, valid[t],
                                     g_seq[t][:, None], n_pages)
        par_pool = _paged_hist_write(par_pool, pt_hist, step + t,
                                     page_size, valid[t], zero_par,
                                     n_pages)

    # score accumulation in strict emission order (the greedy target-
    # only path's left fold)
    acc2 = acc
    for t in range(R):
        acc2 = acc2 + jnp.where(valid[t], lp_seq[t], 0.0)

    # roll back to the state after the LAST emitted token's input was
    # consumed: S_{n_emit} = h_seq[n_emit - 1]
    idx = jnp.maximum(n_emit - 1, 0)
    rows = jnp.arange(C)
    emitted_any = active & (n_emit > 0)
    pick = lambda seq, old: jnp.where(
        emitted_any.reshape((-1,) + (1,) * (old.ndim - 1)),
        seq[idx, rows], old)
    h2 = pick(h_seq, h)
    c2 = pick(c_seq, c)
    prev2 = jnp.where(emitted_any, g_seq[idx, rows], prev)
    acc2 = jnp.where(emitted_any, acc2, acc)
    out = {}
    if hd_seq is not None:
        out['DraftHOut'] = pick(hd_seq, data_of(ins['DraftH'][0]))
        out['DraftCOut'] = pick(cd_seq, data_of(ins['DraftC'][0]))

    fin2 = fin | (valid & (g_seq == end_id)).any(axis=0)
    step2 = step + n_emit
    bad = active & jnp.isnan(acc2)
    done = active & (fin2 | (step2 >= limit) | bad)
    active2 = active & ~done

    out.update({
        'HOut': h2[:, None], 'COut': c2[:, None],
        'PrevIdsOut': prev2[:, None], 'AccOut': acc2[:, None],
        'FinOut': fin2[:, None], 'HistIdsOut': ids_pool,
        'HistParOut': par_pool, 'StepOut': step2, 'ActiveOut': active2,
        'Done': active & ~active2, 'Bad': bad,
        'Accepted': jnp.where(active, accepted, 0)})
    return out


@register('beam_search_decode')
def _beam_search_decode(ins, attrs, ctx):
    """Backtrace stacked per-step beams into sentences.

    Dense contract (replaces the reference's LoDTensorArray walk): Ids and
    Scores are [T, batch, beam]; Parents [T, batch, beam] gives each
    step's source beam. Emits SentenceIds [batch, beam, T] (end_id padded)
    and SentenceScores [batch, beam] final accumulated scores.

    Passed the LoDTensorArrays themselves (the book's While-loop decoder
    verbatim), it backtraces them with the reference Backtrace algorithm
    instead (ops_impl/lod_beam.py) and emits 2-level LoD sentences."""
    from ..lowering import ArrayValue
    if isinstance(ins['Ids'][0], ArrayValue):
        if not ins['Ids'][0].is_seq:
            raise TypeError(
                'beam_search_decode on a LoDTensorArray requires LoD '
                '(beam_search-written) elements; for dense per-step beams '
                'pass stacked [T, batch, beam] tensors + parents instead '
                '(layers.beam_search_decode dense contract)')
        from .lod_beam import beam_search_decode_arrays
        sent_ids, sent_scores = beam_search_decode_arrays(
            ins['Ids'][0], ins['Scores'][0],
            int(attrs.get('beam_size', 0) or 0),
            int(attrs.get('end_id', 0)))
        return {'SentenceIds': sent_ids, 'SentenceScores': sent_scores}
    ids = data_of(ins['Ids'][0]).astype(jnp.int32)        # [T, B, beam]
    scores = data_of(ins['Scores'][0]).astype(jnp.float32)
    T, B, beam = ids.shape
    if ins.get('Parents'):
        # beam_search emits global [B*beam] rows; lineage here is per-source
        parents = data_of(ins['Parents'][0]).astype(jnp.int32) % beam
    else:
        parents = jnp.broadcast_to(jnp.arange(beam)[None, None, :],
                                   (T, B, beam))

    def back(beam_ptr, xs):
        ids_t, par_t = xs                                # [B, beam]
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)
        beam_ptr = jnp.take_along_axis(par_t, beam_ptr, axis=1)
        return beam_ptr, tok

    init = jnp.broadcast_to(jnp.arange(beam)[None, :], (B, beam))
    _, toks_rev = lax.scan(back, init, (jnp.flip(ids, 0), jnp.flip(parents, 0)))
    sent = jnp.flip(jnp.swapaxes(jnp.swapaxes(toks_rev, 0, 1), 1, 2), -1)
    if 'end_id' in attrs:
        end_id = int(attrs['end_id'])
        ended = jnp.cumsum((sent == end_id).astype(jnp.int32), axis=-1) > 0
        prev_ended = jnp.concatenate(
            [jnp.zeros_like(ended[..., :1]), ended[..., :-1]], axis=-1)
        sent = jnp.where(prev_ended, end_id, sent)  # pad past first end_id
    return {'SentenceIds': sent.astype(jnp.int64),
            'SentenceScores': scores[-1].reshape(B, beam)}
