"""Sampled-softmax-family and beam-search rules.

Parity: reference paddle/fluid/operators/{nce,hierarchical_sigmoid,
beam_search,beam_search_decode}_op.* — the reference implements these as
host-side loops over LoD structures (NCE sampling with a CPU sampler,
hsigmoid via MatrixBitCodeFunctor, beam search via LoD pruning).

TPU-first: NCE samples negatives with the step PRNG and evaluates one
batched [B, k+T] gather-matmul (MXU); hsigmoid turns the complete-binary-
tree path walk into a static [B, max_depth] gather + masked BCE; beam
search is a dense [batch, beam] top-k with explicit parent pointers
(replacing LoD lineage), so the whole decode loop stays on device.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import register, data_of, like, SeqValue


@register('nce')
def _nce(ins, attrs, ctx):
    """Noise-contrastive estimation with a uniform noise distribution
    (reference nce_op.h defaults): binary logistic loss on the true class
    vs num_neg sampled classes, logits corrected by log(k*q)."""
    x = data_of(ins['Input'][0])                         # [B, D]
    label = data_of(ins['Label'][0]).astype(jnp.int32)   # [B, T]
    if label.ndim == 1:
        label = label[:, None]
    w = data_of(ins['Weight'][0])                        # [N, D]
    b = data_of(ins['Bias'][0]) if ins.get('Bias') else None   # [N, 1]
    N = int(attrs['num_total_classes'])
    k = int(attrs.get('num_neg_samples', 10))
    B, T = label.shape

    neg = jax.random.randint(ctx.rng(), (k,), 0, N)      # shared noise draw
    log_kq = jnp.log(jnp.asarray(k / N, x.dtype))

    def logits_for(idx_2d):
        wr = jnp.take(w, idx_2d, axis=0)                 # [..., D]
        out = jnp.einsum('bd,b...d->b...', x, wr)
        if b is not None:
            out = out + jnp.take(b[:, 0], idx_2d)
        return out

    true_logit = logits_for(label) - log_kq              # [B, T]
    neg_logit = logits_for(jnp.broadcast_to(neg[None, :], (B, k))) - log_kq

    pos_loss = jnp.sum(jax.nn.softplus(-true_logit), axis=1)
    neg_loss = jnp.sum(jax.nn.softplus(neg_logit), axis=1)
    cost = (pos_loss + neg_loss)[:, None]
    if ins.get('SampleWeight'):
        cost = cost * data_of(ins['SampleWeight'][0]).reshape(B, 1)
    return {'Cost': cost,
            'SampleLogits': jnp.concatenate([true_logit, neg_logit], axis=1),
            'SampleLabels': jnp.concatenate(
                [label, jnp.broadcast_to(neg[None, :], (B, k))],
                axis=1).astype(jnp.int64)}


@register('hierarchical_sigmoid')
def _hsigmoid(ins, attrs, ctx):
    """Complete-binary-tree hierarchical sigmoid (reference
    hierarchical_sigmoid_op.h SimpleCode): leaf for class c is heap node
    c + num_classes; the root->leaf internal nodes and branch bits come
    from the binary representation, evaluated as one masked gather."""
    x = data_of(ins['X'][0])                             # [B, D]
    w = data_of(ins['W'][0])                             # [num_classes-1, D]
    label = data_of(ins['Label'][0]).astype(jnp.int32)
    if label.ndim > 1:
        label = label.reshape(label.shape[0])
    bias = data_of(ins['Bias'][0]) if ins.get('Bias') else None
    C = int(attrs['num_classes'])
    B = x.shape[0]
    max_len = max(1, int(np.ceil(np.log2(C))))

    code = label + C                                     # heap leaf id
    # path length = floor(log2(code)); static loop over max depth
    length = jnp.floor(jnp.log2(code.astype(jnp.float32))).astype(jnp.int32)
    j = jnp.arange(max_len)[None, :]                     # [1, L]
    valid = j < length[:, None]
    shift = jnp.maximum(length[:, None] - j, 1)
    anc = jnp.right_shift(code[:, None], shift)          # ancestor heap ids
    bit = jnp.right_shift(code[:, None], shift - 1) & 1
    idx = jnp.clip(anc - 1, 0, C - 2)                    # weight row

    wr = jnp.take(w, idx, axis=0)                        # [B, L, D]
    pre = jnp.einsum('bd,bld->bl', x, wr)
    if bias is not None:
        pre = pre + jnp.take(bias.reshape(-1), idx)
    pre = jnp.clip(pre, -40.0, 40.0)
    # BCE with logits, target = bit
    loss = jax.nn.softplus(pre) - bit * pre
    out = jnp.sum(jnp.where(valid, loss, 0.0), axis=1, keepdims=True)
    return {'Out': out, 'PreOut': pre}


@register('beam_search')
def _beam_search(ins, attrs, ctx):
    """One beam step on dense [batch*beam, K] candidates: joint top-k over
    beam*K per source, with explicit parent pointers instead of the
    reference's LoD lineage. Finished beams (pre_id == end_id) contribute a
    single end_id candidate carrying their accumulated score forward.

    When the inputs are capacity-form 2-level SeqValues — the book's
    While-loop LoD decoder running verbatim — the step instead follows the
    reference beam_search_op.cc algorithm exactly (ops_impl/lod_beam.py)."""
    from ..lowering import SeqValue
    from .lod_beam import normalize_capacity, beam_search_step
    psc = ins['pre_scores'][0] if ins.get('pre_scores') else None
    if isinstance(psc, SeqValue) and psc.outer_lengths:
        p_ids, p_sc, cids, csc = normalize_capacity(
            ins['pre_ids'][0], psc, ins['ids'][0], ins['scores'][0],
            int(attrs['beam_size']))
        sel_ids, sel_scores, parents = beam_search_step(
            p_ids, p_sc, cids, csc, int(attrs['beam_size']),
            int(attrs['end_id']))
        return {'selected_ids': sel_ids, 'selected_scores': sel_scores,
                'parent_idx': parents.astype(jnp.int64)}
    pre_ids = data_of(ins['pre_ids'][0]).astype(jnp.int32)   # [B*b, 1]
    ids = data_of(ins['ids'][0]).astype(jnp.int32)           # [B*b, K]
    scores = data_of(ins['scores'][0]).astype(jnp.float32)   # [B*b, K]
    beam = int(attrs['beam_size'])
    end_id = int(attrs['end_id'])
    Bb, K = ids.shape
    B = Bb // beam

    finished = (pre_ids[:, 0] == end_id)                 # [B*b]
    if not ins.get('pre_scores'):
        raise ValueError(
            "beam_search requires pre_scores (the previous step's "
            "selected_scores) to carry finished beams' scores forward")
    keep_score = data_of(ins['pre_scores'][0]).astype(jnp.float32).reshape(Bb)
    # finished: only candidate 0 is live (end_id, score carried unchanged)
    cand_scores = jnp.where(
        finished[:, None],
        jnp.where(jnp.arange(K)[None, :] == 0,
                  keep_score[:, None], -jnp.inf),
        scores)
    cand_ids = jnp.where(finished[:, None], end_id, ids)

    flat_scores = cand_scores.reshape(B, beam * K)
    top_scores, top_pos = lax.top_k(flat_scores, beam)   # [B, beam]
    # global flat row index into [B*beam]: directly gatherable for
    # dense beam-state reordering (contrib BeamSearchDecoder)
    parent = top_pos // K + jnp.arange(B)[:, None] * beam
    sel_ids = jnp.take_along_axis(cand_ids.reshape(B, beam * K), top_pos,
                                  axis=1)
    return {'selected_ids': sel_ids.reshape(Bb, 1).astype(jnp.int64),
            'selected_scores': top_scores.reshape(Bb, 1),
            'parent_idx': parent.reshape(Bb).astype(jnp.int64)}


@register('attention_lstm_beam_decode')
def _attention_lstm_beam_decode(ins, attrs, ctx):
    """Whole beam-search generation as ONE lax.scan (TPU-first fusion of the
    reference's While-loop decoder in book test_machine_translation.py:
    decode()): embed -> attend -> LSTM cell -> project -> joint top-k ->
    reorder beams, all inside a single XLA while loop. Weights match the
    training-time `attention_lstm_decoder` op, so a trained model decodes
    with no re-plumbing.

    Inputs: EncOut [B,S,D] (SeqValue), WDec [E+D,4H], UDec [H,4H],
    BDec [1,4H], WAttnQ [H,D], WEmb [V,E], WOut [H,V], BOut [1,V].
    Attrs: beam_size, max_len, start_id, end_id.
    Outputs: SentenceIds [B, beam, max_len], SentenceScores [B, beam]."""
    enc = ins['EncOut'][0]
    enc_data = data_of(enc)                              # [B, S, D]
    if isinstance(enc, SeqValue):
        enc_mask = enc.mask(jnp.float32)
    else:
        enc_mask = jnp.ones(enc_data.shape[:2], jnp.float32)
    w_dec = data_of(ins['WDec'][0])
    u_dec = data_of(ins['UDec'][0])
    b_dec = data_of(ins['BDec'][0]) if ins.get('BDec') else 0.0
    w_q = data_of(ins['WAttnQ'][0])
    w_emb = data_of(ins['WEmb'][0])
    w_out = data_of(ins['WOut'][0])
    b_out = data_of(ins['BOut'][0]) if ins.get('BOut') else 0.0

    beam = int(attrs['beam_size'])
    max_len = int(attrs['max_len'])
    start_id = int(attrs.get('start_id', 0))
    end_id = int(attrs['end_id'])
    B, S, D = enc_data.shape
    H = u_dec.shape[0]
    V = w_out.shape[1]
    Bb = B * beam
    neg = jnp.finfo(jnp.float32).min

    enc_t = jnp.repeat(enc_data, beam, axis=0)           # [Bb, S, D]
    mask_t = jnp.repeat(enc_mask, beam, axis=0)

    h0 = jnp.zeros((Bb, H), enc_data.dtype)
    c0 = jnp.zeros((Bb, H), enc_data.dtype)
    ids0 = jnp.full((Bb,), start_id, jnp.int32)
    # only beam 0 live at t=0 so the first top-k doesn't pick duplicates
    acc0 = jnp.where(jnp.arange(Bb) % beam == 0, 0.0, neg)
    fin0 = jnp.zeros((Bb,), bool)

    def step(carry, _):
        hp, cp, prev_ids, acc, fin = carry
        x_t = jnp.take(w_emb, prev_ids, axis=0)          # [Bb, E]
        q = hp @ w_q
        scores = jnp.einsum('bd,bsd->bs', q, enc_t)
        scores = jnp.where(mask_t > 0, scores, neg)
        alpha = jax.nn.softmax(scores, axis=-1)
        ctx_vec = jnp.einsum('bs,bsd->bd', alpha, enc_t)
        g = jnp.concatenate([x_t, ctx_vec], -1) @ w_dec + hp @ u_dec + b_dec
        gi, gf, gc, go = jnp.split(g, 4, axis=-1)
        c_new = jax.nn.sigmoid(gf) * cp + \
            jax.nn.sigmoid(gi) * jnp.tanh(gc)
        h_new = jax.nn.sigmoid(go) * jnp.tanh(c_new)

        logp = jax.nn.log_softmax(
            (h_new @ w_out + b_out).astype(jnp.float32), axis=-1)
        cand = acc[:, None] + logp                        # [Bb, V]
        # finished beams: single end_id candidate carrying score forward
        onehot_end = (jnp.arange(V)[None, :] == end_id)
        cand = jnp.where(fin[:, None],
                         jnp.where(onehot_end, acc[:, None], neg), cand)

        flat = cand.reshape(B, beam * V)
        top_scores, top_pos = lax.top_k(flat, beam)       # [B, beam]
        parent = (top_pos // V).astype(jnp.int32)         # [B, beam]
        sel_ids = (top_pos % V).astype(jnp.int32)
        gidx = (parent + beam * jnp.arange(B)[:, None]).reshape(Bb)

        h_new = jnp.take(h_new, gidx, axis=0)
        c_new = jnp.take(c_new, gidx, axis=0)
        new_ids = sel_ids.reshape(Bb)
        new_acc = top_scores.reshape(Bb)
        new_fin = jnp.take(fin, gidx) | (new_ids == end_id)
        return (h_new, c_new, new_ids, new_acc, new_fin), \
            (sel_ids, parent, top_scores)

    (_, _, _, accN, _), (ids_seq, par_seq, sc_seq) = lax.scan(
        step, (h0, c0, ids0, acc0, fin0), None, length=max_len)

    def back(beam_ptr, xs):
        ids_t, par_t = xs                                 # [B, beam]
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)
        return jnp.take_along_axis(par_t, beam_ptr, axis=1), tok

    init = jnp.broadcast_to(jnp.arange(beam)[None, :], (B, beam))
    _, toks_rev = lax.scan(back, init,
                           (jnp.flip(ids_seq, 0), jnp.flip(par_seq, 0)))
    sent = jnp.flip(jnp.transpose(toks_rev, (1, 2, 0)), -1)
    return {'SentenceIds': sent.astype(jnp.int64),
            'SentenceScores': accN.reshape(B, beam)}


@register('beam_search_decode')
def _beam_search_decode(ins, attrs, ctx):
    """Backtrace stacked per-step beams into sentences.

    Dense contract (replaces the reference's LoDTensorArray walk): Ids and
    Scores are [T, batch, beam]; Parents [T, batch, beam] gives each
    step's source beam. Emits SentenceIds [batch, beam, T] (end_id padded)
    and SentenceScores [batch, beam] final accumulated scores.

    Passed the LoDTensorArrays themselves (the book's While-loop decoder
    verbatim), it backtraces them with the reference Backtrace algorithm
    instead (ops_impl/lod_beam.py) and emits 2-level LoD sentences."""
    from ..lowering import ArrayValue
    if isinstance(ins['Ids'][0], ArrayValue):
        if not ins['Ids'][0].is_seq:
            raise TypeError(
                'beam_search_decode on a LoDTensorArray requires LoD '
                '(beam_search-written) elements; for dense per-step beams '
                'pass stacked [T, batch, beam] tensors + parents instead '
                '(layers.beam_search_decode dense contract)')
        from .lod_beam import beam_search_decode_arrays
        sent_ids, sent_scores = beam_search_decode_arrays(
            ins['Ids'][0], ins['Scores'][0],
            int(attrs.get('beam_size', 0) or 0),
            int(attrs.get('end_id', 0)))
        return {'SentenceIds': sent_ids, 'SentenceScores': sent_scores}
    ids = data_of(ins['Ids'][0]).astype(jnp.int32)        # [T, B, beam]
    scores = data_of(ins['Scores'][0]).astype(jnp.float32)
    T, B, beam = ids.shape
    if ins.get('Parents'):
        # beam_search emits global [B*beam] rows; lineage here is per-source
        parents = data_of(ins['Parents'][0]).astype(jnp.int32) % beam
    else:
        parents = jnp.broadcast_to(jnp.arange(beam)[None, None, :],
                                   (T, B, beam))

    def back(beam_ptr, xs):
        ids_t, par_t = xs                                # [B, beam]
        tok = jnp.take_along_axis(ids_t, beam_ptr, axis=1)
        beam_ptr = jnp.take_along_axis(par_t, beam_ptr, axis=1)
        return beam_ptr, tok

    init = jnp.broadcast_to(jnp.arange(beam)[None, :], (B, beam))
    _, toks_rev = lax.scan(back, init, (jnp.flip(ids, 0), jnp.flip(parents, 0)))
    sent = jnp.flip(jnp.swapaxes(jnp.swapaxes(toks_rev, 0, 1), 1, 2), -1)
    if 'end_id' in attrs:
        end_id = int(attrs['end_id'])
        ended = jnp.cumsum((sent == end_id).astype(jnp.int32), axis=-1) > 0
        prev_ended = jnp.concatenate(
            [jnp.zeros_like(ended[..., :1]), ended[..., :-1]], axis=-1)
        sent = jnp.where(prev_ended, end_id, sent)  # pad past first end_id
    return {'SentenceIds': sent.astype(jnp.int64),
            'SentenceScores': scores[-1].reshape(B, beam)}
