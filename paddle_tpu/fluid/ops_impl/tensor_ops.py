"""Tensor creation / manipulation rules.

Parity: reference paddle/fluid/operators/{fill_constant,cast,concat,reshape,
transpose,split,gather,scatter,top_k,arg_min_max,one_hot,assign,
uniform_random,gaussian_random,...}_op.*
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like


def _np_dtype(d):
    return jnp.bfloat16 if d in ('bfloat16', jnp.bfloat16) else np.dtype(d)


@register('fill_constant')
def _fill_constant(ins, attrs, ctx):
    shape = tuple(attrs['shape'])
    return {'Out': jnp.full(shape, attrs['value'], dtype=_np_dtype(attrs.get('dtype', 'float32')))}


@register('fill_constant_batch_size_like')
def _fill_constant_bsl(ins, attrs, ctx):
    ref = data_of(ins['Input'][0])
    shape = list(attrs['shape'])
    in_idx = attrs.get('input_dim_idx', 0)
    out_idx = attrs.get('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    return {'Out': jnp.full(tuple(shape), attrs['value'],
                            dtype=_np_dtype(attrs.get('dtype', 'float32')))}


@register('uniform_random')
def _uniform_random(ins, attrs, ctx):
    shape = tuple(attrs['shape'])
    dt = _np_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jax.random.uniform(ctx.rng(), shape, dtype=jnp.float32,
                                      minval=attrs.get('min', -1.0),
                                      maxval=attrs.get('max', 1.0)).astype(dt)}


@register('uniform_random_batch_size_like')
def _uniform_random_bsl(ins, attrs, ctx):
    ref = data_of(ins['Input'][0])
    shape = list(attrs['shape'])
    shape[attrs.get('output_dim_idx', 0)] = ref.shape[attrs.get('input_dim_idx', 0)]
    dt = _np_dtype(attrs.get('dtype', 'float32'))
    return {'Out': jax.random.uniform(ctx.rng(), tuple(shape), dtype=jnp.float32,
                                      minval=attrs.get('min', -1.0),
                                      maxval=attrs.get('max', 1.0)).astype(dt)}


@register('gaussian_random')
def _gaussian_random(ins, attrs, ctx):
    shape = tuple(attrs['shape'])
    dt = _np_dtype(attrs.get('dtype', 'float32'))
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * jax.random.normal(
        ctx.rng(), shape, dtype=jnp.float32)
    return {'Out': out.astype(dt)}


@register('gaussian_random_batch_size_like')
def _gaussian_random_bsl(ins, attrs, ctx):
    ref = data_of(ins['Input'][0])
    shape = list(attrs['shape'])
    shape[attrs.get('output_dim_idx', 0)] = ref.shape[attrs.get('input_dim_idx', 0)]
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * jax.random.normal(
        ctx.rng(), tuple(shape), dtype=jnp.float32)
    return {'Out': out.astype(_np_dtype(attrs.get('dtype', 'float32')))}


@register('truncated_gaussian_random')
def _truncated_gaussian_random(ins, attrs, ctx):
    shape = tuple(attrs['shape'])
    out = attrs.get('mean', 0.0) + attrs.get('std', 1.0) * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=jnp.float32)
    return {'Out': out.astype(_np_dtype(attrs.get('dtype', 'float32')))}


@register('cast')
def _cast(ins, attrs, ctx):
    x = ins['X'][0]
    return {'Out': like(x, data_of(x).astype(_np_dtype(attrs['out_dtype'])))}


@register('concat')
def _concat(ins, attrs, ctx):
    from ..lowering import first_seq, SeqValue
    vs = ins['X']
    xs = [data_of(v) for v in vs]
    axis = attrs.get('axis', 0)
    out = jnp.concatenate(xs, axis=axis)
    seq = first_seq(*vs)
    if seq is None:
        return {'Out': out}
    all_seq = all(isinstance(v, SeqValue) for v in vs)
    if axis == 0 and all_seq:
        # batch concat: stack lengths too
        return {'Out': SeqValue(out, jnp.concatenate([v.lengths for v in vs]))}
    if axis == 1 and all_seq:
        # time concat: every row's valid length is the sum... only exact when
        # inputs are right-padded contiguously; true when each input is
        # full-length, else the padding interleaves — reject to avoid
        # silently masking wrong tokens.
        lens = vs[0].lengths
        for v in vs[1:]:
            lens = lens + v.lengths
        return {'Out': SeqValue(out, lens)}
    if axis in (0, 1):
        return {'Out': out}
    return {'Out': like(seq, out)}


@register('assign')
def _assign(ins, attrs, ctx):
    return {'Out': ins['X'][0]}


@register('shape')
def _shape(ins, attrs, ctx):
    x = data_of(ins['Input'][0])
    return {'Out': jnp.asarray(x.shape, dtype=jnp.int32)}


@register('reshape')
def _reshape(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    shape = [int(d) for d in attrs['shape']]
    # Fluid semantics (operators/reshape_op.cc): 0 means "copy input dim",
    # one -1 is inferred.
    out_shape = []
    for i, d in enumerate(shape):
        if d == 0:
            out_shape.append(x.shape[i])
        else:
            out_shape.append(d)
    return {'Out': x.reshape(out_shape)}


@register('squeeze')
def _squeeze(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axes = attrs.get('axes')
    return {'Out': jnp.squeeze(x, axis=tuple(axes) if axes else None)}


@register('unsqueeze')
def _unsqueeze(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    out = x
    for a in sorted(attrs['axes']):
        out = jnp.expand_dims(out, a)
    return {'Out': out}


@register('transpose')
def _transpose(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.transpose(x, attrs['axis'])}


@register('split')
def _split(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axis = attrs.get('axis', -1)
    num = attrs.get('num', 0)
    sections = attrs.get('sections')
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {'Out': list(outs)}


@register('stack')
def _stack(ins, attrs, ctx):
    xs = [data_of(v) for v in ins['X']]
    return {'Y': jnp.stack(xs, axis=attrs.get('axis', 0))}


@register('flatten')
def _flatten(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axis = attrs.get('axis', 1)
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return {'Out': x.reshape(lead, -1)}


@register('pad')
def _pad(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    p = attrs['paddings']
    pads = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {'Out': jnp.pad(x, pads, constant_values=attrs.get('pad_value', 0.0))}


@register('crop')
def _crop(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    offsets = attrs.get('offsets')
    shape = attrs.get('shape')
    if 'Y' in ins and ins['Y']:
        shape = data_of(ins['Y'][0]).shape
    # a -1 entry means "from the offset to the end of that dim"
    shape = [x.shape[i] - offsets[i] if s == -1 else s
             for i, s in enumerate(shape)]
    return {'Out': jax.lax.dynamic_slice(x, offsets, shape)}


@register('slice')
def _slice(ins, attrs, ctx):
    x = data_of(ins['Input'][0])
    axes = attrs['axes']
    starts = attrs['starts']
    ends = attrs['ends']
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        dim = x.shape[a]
        s2 = s + dim if s < 0 else min(s, dim)
        e2 = e + dim if e < 0 else min(e, dim)
        idx[a] = slice(s2, e2)
    return {'Out': x[tuple(idx)]}


@register('gather')
def _gather(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    index = data_of(ins['Index'][0]).astype(jnp.int32)
    return {'Out': jnp.take(x, index, axis=0)}


@register('expand')
def _expand(ins, attrs, ctx):
    """reference operators/expand_op.cc: tile each dim by expand_times."""
    x = data_of(ins['X'][0])
    return {'Out': jnp.tile(x, tuple(attrs['expand_times']))}


@register('scatter')
def _scatter(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    ids = data_of(ins['Ids'][0]).astype(jnp.int32)
    upd = data_of(ins['Updates'][0])
    return {'Out': x.at[ids].set(upd)}


@register('top_k')
def _top_k(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    k = attrs['k']
    vals, idx = jax.lax.top_k(x, k)
    return {'Out': vals, 'Indices': idx.astype(jnp.int64)}


@register('arg_min')
def _arg_min(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.argmin(x, axis=attrs.get('axis', 0)).astype(jnp.int64)}


@register('arg_max')
def _arg_max(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.argmax(x, axis=attrs.get('axis', 0)).astype(jnp.int64)}


@register('argsort')
def _argsort(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axis = attrs.get('axis', -1)
    idx = jnp.argsort(x, axis=axis)
    return {'Out': jnp.sort(x, axis=axis), 'Indices': idx.astype(jnp.int64)}


@register('one_hot')
def _one_hot(ins, attrs, ctx):
    x = data_of(ins['X'][0]).astype(jnp.int32)
    depth = attrs['depth']
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = jnp.squeeze(x, -1)
    out = jax.nn.one_hot(x, depth, dtype=jnp.float32)
    return {'Out': like(ins['X'][0], out)}


@register('reverse')
def _reverse(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    axes = attrs['axis']
    if not isinstance(axes, (list, tuple)):
        axes = [axes]
    return {'Out': jnp.flip(x, axis=tuple(axes))}


@register('multiplex')
def _multiplex(ins, attrs, ctx):
    ids = data_of(ins['Ids'][0]).astype(jnp.int32).reshape(-1)
    xs = jnp.stack([data_of(v) for v in ins['X']], axis=0)  # [n, B, ...]
    rows = jnp.arange(ids.shape[0])
    return {'Out': xs[ids, rows]}


@register('increment')
def _increment(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': x + jnp.asarray(attrs.get('step', 1.0), dtype=x.dtype)}


@register('is_empty')
def _is_empty(ins, attrs, ctx):
    from .lod_beam import is_beam_form, is_empty_beam
    if is_beam_form(ins['X'][0]):
        # beam decode: "empty" is a RUNTIME property (all sources pruned),
        # the While-loop's stop condition in the book decoder
        return {'Out': is_empty_beam(ins['X'][0])}
    x = data_of(ins['X'][0])
    return {'Out': jnp.asarray(x.size == 0)}


@register('label_smooth')
def _label_smooth(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    eps = attrs.get('epsilon', 0.0)
    if 'PriorDist' in ins and ins['PriorDist']:
        prior = data_of(ins['PriorDist'][0])
        out = (1 - eps) * x + eps * prior
    else:
        out = (1 - eps) * x + eps / x.shape[-1]
    return {'Out': like(ins['X'][0], out)}


@register('random_crop')
def _random_crop(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    shape = attrs['shape']  # crop shape for trailing dims
    lead = x.ndim - len(shape)
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        limit = x.shape[lead + i] - s
        key, sub = jax.random.split(key)
        starts.append(jax.random.randint(sub, (), 0, max(limit, 0) + 1))
    start_idx = [jnp.asarray(0)] * lead + starts
    sizes = list(x.shape[:lead]) + list(shape)
    return {'Out': jax.lax.dynamic_slice(x, start_idx, sizes)}


@register('assign_value')
def _assign_value(ins, attrs, ctx):
    vals = np.asarray(attrs['values'], dtype=_np_dtype(attrs.get('dtype', 'float32')))
    return {'Out': jnp.asarray(vals.reshape(attrs['shape']))}
