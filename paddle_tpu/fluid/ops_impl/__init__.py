"""Lowering rules for every op type (Fluid op -> pure JAX).

Importing this package registers all rules. Grouped roughly like the
reference's paddle/fluid/operators/ tree, but each op is one JAX rule
instead of a C++ OpKernel pair (CPU/CUDA).
"""
from . import math_ops  # noqa: F401
from . import tensor_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import optim_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import block_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import crf_ctc_ops  # noqa: F401
from . import sampled_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from . import embedding_ops  # noqa: F401
from . import extra_ops  # noqa: F401
from . import quant_ops  # noqa: F401
