"""CRF / CTC / edit-distance / chunk-eval rules.

Parity: reference paddle/fluid/operators/{linear_chain_crf,crf_decoding,
ctc_align,edit_distance,warpctc,chunk_eval}_op.* — the reference walks
LoD-flattened sequences with per-sequence CPU loops (and hands CTC to the
external warp-ctc CUDA library).

TPU-first: every rule here is a masked dense computation over padded
[batch, max_len, ...] SeqValues. The CRF forward/Viterbi and the CTC
forward algorithm are lax.scan recurrences in log-space (stable, static
shapes, MXU-friendly batched inner steps); edit distance scans DP rows;
chunk_eval is pure vectorised boundary logic. No host loops, no external
kernels — the whole family jit-compiles into the training step.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import register, data_of, like, SeqValue

_NEG = -1e30


def _ids2d(v):
    """SeqValue/array of ids [B,T,1] or [B,T] -> int32 [B,T]."""
    x = data_of(v).astype(jnp.int32)
    if x.ndim == 3 and x.shape[-1] == 1:
        x = jnp.squeeze(x, -1)
    return x


def _lengths(v, T):
    if isinstance(v, SeqValue):
        return v.lengths.astype(jnp.int32)
    return jnp.full((data_of(v).shape[0],), T, jnp.int32)


# ---------------------------------------------------------------------------
# linear_chain_crf
# ---------------------------------------------------------------------------

@register('linear_chain_crf')
def _linear_chain_crf(ins, attrs, ctx):
    """Transition layout (reference linear_chain_crf_op.h): row 0 = start
    weights a, row 1 = stop weights b, rows 2: = pairwise w[prev, cur].
    Output LogLikelihood is the per-sequence negative log-likelihood
    (the book models feed it straight into mean() as the cost)."""
    em_v = ins['Emission'][0]
    emission = data_of(em_v).astype(jnp.float32)        # [B, T, C]
    transition = data_of(ins['Transition'][0]).astype(jnp.float32)
    label = _ids2d(ins['Label'][0])                      # [B, T]
    B, T, C = emission.shape
    a, b, w = transition[0], transition[1], transition[2:]
    lens = _lengths(em_v, T)

    valid = (jnp.arange(T)[None, :] < lens[:, None])     # [B, T]

    # --- log partition: alpha recursion over time -------------------------
    alpha0 = a[None, :] + emission[:, 0]                 # [B, C]

    def fwd(alpha, xs):
        em_t, valid_t = xs                               # [B, C], [B]
        nxt = jax.nn.logsumexp(alpha[:, :, None] + w[None], axis=1) + em_t
        alpha = jnp.where(valid_t[:, None], nxt, alpha)
        return alpha, alpha

    alphaT, alphas = lax.scan(
        fwd, alpha0,
        (jnp.swapaxes(emission, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:]))
    log_z = jax.nn.logsumexp(alphaT + b[None, :], axis=-1)          # [B]

    # --- gold path score --------------------------------------------------
    em_score = jnp.sum(
        jnp.where(valid,
                  jnp.take_along_axis(emission, label[:, :, None],
                                      axis=2)[:, :, 0], 0.0), axis=1)
    start_score = a[label[:, 0]]
    last_idx = jnp.maximum(lens - 1, 0)
    last_lab = jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]
    stop_score = b[last_lab]
    trans_pairs = w[label[:, :-1], label[:, 1:]]                    # [B, T-1]
    pair_valid = valid[:, 1:]
    trans_score = jnp.sum(jnp.where(pair_valid, trans_pairs, 0.0), axis=1)
    path = em_score + start_score + stop_score + trans_score

    nll = (log_z - path)[:, None]                                    # [B, 1]
    alphas_full = jnp.concatenate([alpha0[:, None], jnp.swapaxes(alphas, 0, 1)],
                                  axis=1)
    return {'LogLikelihood': nll,
            'Alpha': like(em_v, alphas_full),
            'EmissionExps': like(em_v, jnp.exp(emission - jnp.max(
                emission, axis=-1, keepdims=True))),
            'TransitionExps': jnp.exp(transition)}


@register('crf_decoding')
def _crf_decoding(ins, attrs, ctx):
    """Viterbi decode; with Label given, emits per-token correctness
    (reference crf_decoding_op.h flips the path to a 0/1 mismatch mask)."""
    em_v = ins['Emission'][0]
    emission = data_of(em_v).astype(jnp.float32)         # [B, T, C]
    transition = data_of(ins['Transition'][0]).astype(jnp.float32)
    B, T, C = emission.shape
    a, b, w = transition[0], transition[1], transition[2:]
    lens = _lengths(em_v, T)
    valid = (jnp.arange(T)[None, :] < lens[:, None])

    delta0 = a[None, :] + emission[:, 0]

    def fwd(delta, xs):
        em_t, valid_t, t = xs
        scores = delta[:, :, None] + w[None]             # [B, C, C]
        best_prev = jnp.argmax(scores, axis=1)           # [B, C]
        nxt = jnp.max(scores, axis=1) + em_t
        new_delta = jnp.where(valid_t[:, None], nxt, delta)
        ptr = jnp.where(valid_t[:, None], best_prev,
                        jnp.arange(C)[None, :])          # identity when padded
        return new_delta, ptr

    deltaT, ptrs = lax.scan(
        fwd, delta0,
        (jnp.swapaxes(emission, 0, 1)[1:], jnp.swapaxes(valid, 0, 1)[1:],
         jnp.arange(1, T)))
    last = jnp.argmax(deltaT + b[None, :], axis=-1)      # [B]

    def back(state, ptr_t):
        state = jnp.take_along_axis(ptr_t, state[:, None], axis=1)[:, 0]
        return state, state

    _, rev_path = lax.scan(back, last, ptrs, reverse=True)
    path = jnp.concatenate([jnp.swapaxes(rev_path, 0, 1), last[:, None]],
                           axis=1) if T > 1 else last[:, None]
    path = jnp.where(valid, path, 0).astype(jnp.int64)

    if 'Label' in ins and ins['Label']:
        label = _ids2d(ins['Label'][0]).astype(jnp.int64)
        path = jnp.where(valid, (path == label).astype(jnp.int64), 0)
    return {'ViterbiPath': like(em_v, path[:, :, None])}


# ---------------------------------------------------------------------------
# CTC
# ---------------------------------------------------------------------------

@register('ctc_align')
def _ctc_align(ins, attrs, ctx):
    """Greedy CTC decode: argmax per frame, merge repeats, drop blanks.
    Compaction keeps static shapes: kept tokens are stably moved left."""
    x_v = ins['Input'][0]
    x = data_of(x_v)
    if x.ndim == 3:                                      # probs [B,T,C]
        ids = jnp.argmax(x, axis=-1).astype(jnp.int32)
    else:
        ids = _ids2d(x_v)
    B, T = ids.shape
    blank = int(attrs.get('blank', 0))
    merge = bool(attrs.get('merge_repeated', True))
    lens = _lengths(x_v, T)
    valid = (jnp.arange(T)[None, :] < lens[:, None])

    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), ids[:, :-1]],
                           axis=1)
    keep = valid & (ids != blank)
    if merge:
        keep = keep & (ids != prev)
    packed, new_lens = _compact(ids, keep)
    packed = jnp.where(jnp.arange(T)[None, :] < new_lens[:, None], packed, 0)
    return {'Output': SeqValue(packed[:, :, None].astype(jnp.int64), new_lens)}


@register('warpctc')
def _warpctc(ins, attrs, ctx):
    """CTC loss, log-space alpha recursion over the blank-interleaved label
    (Graves 2006) — replaces the external warp-ctc kernel with a lax.scan
    that XLA fuses into the train step; jax.grad differentiates it directly
    so the reference's hand-written WarpCTCGrad output is vestigial."""
    logits_v = ins['Logits'][0]
    logits = data_of(logits_v).astype(jnp.float32)       # [B, T, C]
    label = _ids2d(ins['Label'][0])                       # [B, L]
    B, T, C = logits.shape
    L = label.shape[1]
    blank = int(attrs.get('blank', 0))
    t_lens = _lengths(logits_v, T)
    l_lens = _lengths(ins['Label'][0], L)

    logp = jax.nn.log_softmax(logits, axis=-1)
    # extended sequence e[s]: blank, y1, blank, y2, ..., blank — length 2L+1
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(label)
    ext_len = 2 * l_lens + 1
    s_idx = jnp.arange(S)[None, :]
    in_ext = s_idx < ext_len[:, None]

    # can skip from s-2 to s when e[s] != blank and e[s] != e[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]],
                             axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    lp_ext0 = jnp.take_along_axis(logp[:, 0], ext, axis=1)  # [B, S]
    alpha0 = jnp.full((B, S), _NEG)
    alpha0 = alpha0.at[:, 0].set(lp_ext0[:, 0])
    alpha0 = alpha0.at[:, 1].set(jnp.where(l_lens >= 1, lp_ext0[:, 1], _NEG))

    def step(alpha, xs):
        lp_t, valid_t = xs                               # [B, C], [B]
        lp_ext = jnp.take_along_axis(lp_t, ext, axis=1)  # [B, S]
        a1 = jnp.concatenate([jnp.full((B, 1), _NEG), alpha[:, :-1]], axis=1)
        a2 = jnp.concatenate([jnp.full((B, 2), _NEG), alpha[:, :-2]], axis=1)
        a2 = jnp.where(can_skip, a2, _NEG)
        nxt = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2) + lp_ext
        nxt = jnp.where(in_ext, nxt, _NEG)
        return jnp.where(valid_t[:, None], nxt, alpha), None

    valid_t = (jnp.arange(T)[None, :] < t_lens[:, None])
    alphaT, _ = lax.scan(step, alpha0,
                         (jnp.swapaxes(logp, 0, 1)[1:],
                          jnp.swapaxes(valid_t, 0, 1)[1:]))

    idx_last = jnp.maximum(ext_len - 1, 0)
    idx_prev = jnp.maximum(ext_len - 2, 0)
    a_last = jnp.take_along_axis(alphaT, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alphaT, idx_prev[:, None], axis=1)[:, 0]
    # empty label (ext_len == 1): only the all-blank path exists
    ll = jnp.logaddexp(a_last, jnp.where(ext_len >= 2, a_prev, _NEG))
    loss = -ll
    if attrs.get('norm_by_times'):
        loss = loss / jnp.maximum(t_lens, 1).astype(jnp.float32)
    return {'Loss': loss[:, None], 'WarpCTCGrad': None}


# ---------------------------------------------------------------------------
# edit_distance
# ---------------------------------------------------------------------------

def _compact(ids, keep):
    """Stable left-compaction of kept tokens (static shapes): sort positions
    by (dropped, index), recount lengths."""
    T = ids.shape[1]
    order = jnp.argsort(jnp.where(keep, jnp.arange(T)[None, :], T + 1), axis=1)
    packed = jnp.take_along_axis(ids, order, axis=1)
    new_lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    return packed, new_lens


def _strip_tokens(ids, lens, ignored):
    """Remove ignored token ids, compacting left."""
    T = ids.shape[1]
    keep = (jnp.arange(T)[None, :] < lens[:, None])
    for tok in ignored:
        keep = keep & (ids != int(tok))
    return _compact(ids, keep)


@register('edit_distance')
def _edit_distance(ins, attrs, ctx):
    """Levenshtein DP: scan over hypothesis tokens carrying one DP row
    (reference edit_distance_op.h runs the quadratic loop per sequence on
    the host; here all batch rows advance in lockstep on device)."""
    hyp_v, ref_v = ins['Hyps'][0], ins['Refs'][0]
    hyp = _ids2d(hyp_v)
    ref = _ids2d(ref_v)
    B, Th = hyp.shape
    Tr = ref.shape[1]
    h_lens = _lengths(hyp_v, Th)
    r_lens = _lengths(ref_v, Tr)
    ignored = attrs.get('ignored_tokens') or []
    if ignored:
        hyp, h_lens = _strip_tokens(hyp, h_lens, ignored)
        ref, r_lens = _strip_tokens(ref, r_lens, ignored)

    row0 = jnp.broadcast_to(jnp.arange(Tr + 1, dtype=jnp.float32)[None, :],
                            (B, Tr + 1))

    def step(row, xs):
        h_t, i = xs                                       # [B], scalar idx
        sub_cost = (ref != h_t[:, None]).astype(jnp.float32)
        # new_row computed left-to-right; deletion dependency needs a scan
        # over columns — use the standard trick: costs without the running
        # min first, then an associative prefix to fix deletions.
        ins_del_sub = jnp.minimum(row[:, 1:] + 1.0,       # deletion (from up)
                                  row[:, :-1] + sub_cost)  # substitution
        first = row[:, :1] + 1.0                          # new_row[0] = i
        # prefix pass for insertions: new[j] = min(cand[j], new[j-1] + 1)
        cand = jnp.concatenate([first, ins_del_sub], axis=1)
        shift = jnp.cumsum(jnp.ones_like(cand), axis=1)
        fixed = lax.associative_scan(jnp.minimum, cand - shift, axis=1) + shift
        active = (i < h_lens)[:, None]
        new_row = jnp.where(active, fixed, row)
        return new_row, None

    rowN, _ = lax.scan(step, row0, (jnp.swapaxes(hyp, 0, 1),
                                    jnp.arange(Th)))
    dist = jnp.take_along_axis(rowN, r_lens[:, None].astype(jnp.int32),
                               axis=1)[:, 0]
    if attrs.get('normalized', True):
        dist = dist / jnp.maximum(r_lens, 1).astype(jnp.float32)
    return {'Out': dist[:, None],
            'SequenceNum': jnp.asarray(B, jnp.int64)}


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------

def _chunk_bounds(tags, lens, scheme, num_types, excluded):
    """Per-position (start, end, type, in_chunk) masks for a tag sequence.

    Tag encoding (reference chunk_eval_op.h): tag = type * tag_num + flag,
    O tag = num_types * tag_num (or anything beyond)."""
    B, T = tags.shape
    tag_num = {'plain': 1, 'IOB': 2, 'IOE': 2, 'IOBES': 4}[scheme]
    typ = tags // tag_num
    flag = tags % tag_num
    valid = (jnp.arange(T)[None, :] < lens[:, None])
    non_o = valid & (typ < num_types)
    for ex in excluded:
        non_o = non_o & (typ != int(ex))

    p_typ = jnp.concatenate([jnp.full((B, 1), -1, tags.dtype), typ[:, :-1]], 1)
    p_flag = jnp.concatenate([jnp.full((B, 1), -1, tags.dtype), flag[:, :-1]], 1)
    p_in = jnp.concatenate([jnp.zeros((B, 1), bool), non_o[:, :-1]], 1)
    n_typ = jnp.concatenate([typ[:, 1:], jnp.full((B, 1), -1, tags.dtype)], 1)
    n_flag = jnp.concatenate([flag[:, 1:], jnp.full((B, 1), -1, tags.dtype)], 1)
    n_in = jnp.concatenate([non_o[:, 1:], jnp.zeros((B, 1), bool)], 1)
    n_valid = jnp.concatenate([valid[:, 1:], jnp.zeros((B, 1), bool)], 1)
    n_in = n_in & n_valid

    brk_prev = (~p_in) | (p_typ != typ)
    brk_next = (~n_in) | (n_typ != typ)
    if scheme == 'plain':
        start = non_o & brk_prev
        end = non_o & brk_next
    elif scheme == 'IOB':                                 # B=0, I=1
        start = non_o & ((flag == 0) | brk_prev)
        end = non_o & (brk_next | (n_flag == 0))
    elif scheme == 'IOE':                                 # I=0, E=1
        start = non_o & (brk_prev | (p_flag == 1))
        end = non_o & ((flag == 1) | brk_next)
    else:                                                 # IOBES: B,I,E,S
        start = non_o & ((flag == 0) | (flag == 3) | brk_prev
                         | (p_flag == 2) | (p_flag == 3))
        end = non_o & ((flag == 2) | (flag == 3) | brk_next
                       | (n_flag == 0) | (n_flag == 3))
    return start, end, typ, non_o


def _end_of_chunk_at(start, end, T):
    """e[t] = index of first end >= t (for matching chunk extents)."""
    idx = jnp.arange(T)[None, :]
    cand = jnp.where(end, idx, T + 1)
    rev = jnp.flip(cand, axis=1)
    e = jnp.flip(lax.associative_scan(jnp.minimum, rev, axis=1), axis=1)
    return e


@register('chunk_eval')
def _chunk_eval(ins, attrs, ctx):
    inf_v, lab_v = ins['Inference'][0], ins['Label'][0]
    inf = _ids2d(inf_v)
    lab = _ids2d(lab_v)
    B, T = inf.shape
    lens = _lengths(lab_v, T)
    scheme = attrs.get('chunk_scheme', 'IOB')
    num_types = int(attrs['num_chunk_types'])
    excluded = attrs.get('excluded_chunk_types') or []

    s_i, e_i, t_i, _ = _chunk_bounds(inf, lens, scheme, num_types, excluded)
    s_l, e_l, t_l, _ = _chunk_bounds(lab, lens, scheme, num_types, excluded)
    ee_i = _end_of_chunk_at(s_i, e_i, T)
    ee_l = _end_of_chunk_at(s_l, e_l, T)

    n_inf = jnp.sum(s_i)
    n_lab = jnp.sum(s_l)
    correct = jnp.sum(s_i & s_l & (t_i == t_l) & (ee_i == ee_l))

    nc = correct.astype(jnp.float32)
    precision = jnp.where(n_inf > 0, nc / jnp.maximum(n_inf, 1), 0.0)
    recall = jnp.where(n_lab > 0, nc / jnp.maximum(n_lab, 1), 0.0)
    f1 = jnp.where(nc > 0, 2 * precision * recall
                   / jnp.maximum(precision + recall, 1e-12), 0.0)
    return {'Precision': precision.astype(jnp.float32),
            'Recall': recall.astype(jnp.float32),
            'F1-Score': f1.astype(jnp.float32),
            'NumInferChunks': n_inf.astype(jnp.int64),
            'NumLabelChunks': n_lab.astype(jnp.int64),
            'NumCorrectChunks': correct.astype(jnp.int64)}
