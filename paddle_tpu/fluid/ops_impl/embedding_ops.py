"""Distributed `lookup_table` lowering (docs/embedding.md).

Parity: reference lookup_table_op.cc with `is_distributed=True` rewired by
DistributeTranspiler into per-pserver row shards and gRPC prefetch ops.
TPU-first: the table is row-sharded over a mesh axis by its GSPMD
annotation (`ParamAttr(sharding=(axis, None))`) and the lookup lowers to
the all_to_all wire in paddle_tpu.embedding.lookup — bucket ids by owning
shard, dedup, ONE all_to_all out with the queries, local gather, one
all_to_all back with the rows (the parallel/moe.py exchange pattern).

The layer (layers/nn.py:embedding) stamps the table's row axis on the op
as `dist_axis`; this rule takes the wire path only when the step is
compiled against a mesh that declares that axis — everywhere else
(build-time shape inference, single-device runs, program_lint) the caller
(sequence_ops._lookup_table) keeps the dense gather, so the two paths are
fetch-equivalent by construction (drilled in tests/test_embedding.py).
"""
import warnings

import jax
import jax.numpy as jnp

from ... import obs
from ..lowering import data_of, like


def dist_lookup_applies(attrs, ctx):
    """Does this lookup_table op take the sharded wire? Requires the
    layer-stamped `dist_axis` AND a step mesh declaring that axis — the
    dense gather is the correct lowering everywhere else (abstract_eval
    runs with ctx.mesh=None and must agree on shapes)."""
    axis = attrs.get('dist_axis')
    return (bool(attrs.get('is_distributed')) and axis is not None
            and ctx.mesh is not None
            and axis in getattr(ctx.mesh, 'shape', {})
            # already manual over mesh axes (a pipeline-region body):
            # opening a nested shard_map would fail — the stage keeps
            # the dense gather
            and not ctx.manual_axes)


def lookup_table_dist(ins, attrs, ctx):
    """The sharded branch of the `lookup_table` rule. Mirrors the dense
    rule's conventions (squeeze trailing id column, padding_idx zeroing,
    SeqValue/beam re-wrapping) with the gather replaced by the
    all_to_all exchange. Falls back to the dense gather — loudly — when
    the annotated vocab cannot tile over the axis (the statically-checked
    EmbeddingShardUntileable case reached at runtime)."""
    from ...embedding.lookup import sharded_lookup, wire_stats
    from .sequence_ops import _lookup_table_dense

    axis = attrs['dist_axis']
    ws = ctx.mesh.shape[axis]
    w = data_of(ins['W'][0])
    if w.shape[0] % ws:
        warnings.warn(
            'lookup_table(is_distributed=True): vocab %d does not tile '
            'over mesh axis %r size %d — falling back to the dense '
            'gather (pad the table via embedding.pad_vocab)'
            % (w.shape[0], axis, ws), RuntimeWarning)
        return _lookup_table_dense(ins, attrs, ctx)

    ids_v = ins['Ids'][0]
    ids = data_of(ids_v).astype(jnp.int32)
    if ids.shape and ids.shape[-1] == 1:
        ids = jnp.squeeze(ids, -1)
    pad = attrs.get('padding_idx')
    pad = pad if pad is not None and pad >= 0 else None
    out = sharded_lookup(w, ids, ctx.mesh, axis, padding_idx=pad)
    if isinstance(w, jax.core.Tracer):
        # once per TRACE (= once per compiled cache key; the jitted
        # steady state re-emits nothing): the wire geometry of this
        # lookup. The Tracer guard keeps the eager debug/profiler path
        # — which executes the rule EVERY step — from flooding the run
        # log with one event per step.
        obs.event('embedding.lookup', axis=axis,
                  **wire_stats(int(ids.size), int(w.shape[0]),
                               int(w.shape[1]), ws,
                               itemsize=int(w.dtype.itemsize)))
    from .lod_beam import is_beam_form
    if is_beam_form(ids_v) and out.ndim == ids.ndim + 1:
        # capacity-form beam rows [R] embed to [R, 1, E] (decode idiom —
        # same shape contract as the dense rule)
        out = out[:, None]
    return {'Out': like(ids_v, out)}
