"""Optimizer update rules.

Parity: reference paddle/fluid/operators/{sgd,momentum,adam,adagrad,adamax,
decayed_adagrad,rmsprop,ftrl,adadelta}_op.* — each lowers to a pure update
fused into the same XLA module as forward+backward, so the whole train step
is one device launch (the reference dispatches one CUDA kernel per param per
optimizer op).
"""
import jax
import jax.numpy as jnp

from ..lowering import register, data_of, SparseRows, use_kernel


def _lr(ins):
    return data_of(ins['LearningRate'][0]).reshape(())


def _merge_sparse(g, ctx=None):
    """Merge duplicate ids of a SparseRows grad (reference MergeAdd,
    operators/math/selected_rows_functor.cc): nonlinear updates (adagrad's
    g^2, adam's moments) must see each touched row ONCE with its summed
    gradient. Static shapes: sort the N occurrences, segment-sum into at
    most N merged rows, and return (uids int32[N], merged [N, D],
    valid bool[N]) where invalid slots carry zero rows and id 0 — callers
    mask their update deltas with `valid` so the padding rows are no-ops.

    Sharded case (docs/embedding.md): when the step is compiled against a
    mesh (ctx.mesh) the merge's [N, *] intermediates are PINNED replicated
    — N is batch-sized, and without the pin GSPMD has to invent layouts
    for the argsort/segment-sum chain from the (axis-sharded) cotangents
    feeding it, which is exactly the replicate-then-repartition class the
    remat detector flags. The row scatter the CALLER then does against the
    row-sharded table partitions per shard (each shard applies the deltas
    for rows it owns), and the step's out-sharding constraint keeps the
    table's layout a fixed point — the dense [vocab, dim] gradient never
    exists under either layout.

    The sort/segment/unsort core is embedding.lookup.dedup_plan — ONE
    definition of the static-shape dedup invariant serves both the
    lookup wire's query side and this merge."""
    from ...embedding.lookup import dedup_plan
    ids, rows = g.ids, g.rows
    if ctx is not None and getattr(ctx, 'mesh', None) is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        rep = NamedSharding(ctx.mesh, PartitionSpec())
        ids = jax.lax.with_sharding_constraint(ids, rep)
        rows = jax.lax.with_sharding_constraint(rows, rep)
    n = ids.shape[0]
    uids, seg, order, n_unique = dedup_plan(ids.astype(jnp.int32))
    merged = jax.ops.segment_sum(rows[order], seg, num_segments=n)
    valid = jnp.arange(n) < n_unique
    # invalid slots carry dedup_plan's sentinel id: clamp to 0 so the
    # callers' moment GATHERS at uids stay in-bounds (their scattered
    # deltas are already masked with `valid`)
    uids = jnp.where(valid, uids, 0)
    return uids, merged, valid


@register('sgd')
def _sgd(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = ins['Grad'][0]
    if isinstance(g, SparseRows):
        # index-based row update (reference sgd_op.h SelectedRows branch):
        # scatter-add handles duplicate ids exactly like the dense path
        # (SGD is linear in the gradient), and the vocab-sized dense grad
        # buffer never exists
        return {'ParamOut': p.at[g.ids].add(-_lr(ins) * g.rows)}
    return {'ParamOut': p - _lr(ins) * data_of(g)}


@register('momentum')
def _momentum(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    v = data_of(ins['Velocity'][0])
    mu = attrs['mu']
    lr = _lr(ins)
    v_out = mu * v + g
    if attrs.get('use_nesterov', False):
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {'ParamOut': p_out, 'VelocityOut': v_out}


@register('adagrad')
def _adagrad(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = ins['Grad'][0]
    m = data_of(ins['Moment'][0])
    eps = attrs.get('epsilon', 1e-6)
    lr = _lr(ins)
    if isinstance(g, SparseRows):
        # touched-rows-only update on merged duplicates (reference
        # adagrad_op.h SelectedRows branch: MergeAdd then per-row update).
        # Deltas (not absolute values) are scattered so the zero-padded
        # invalid merge slots are exact no-ops under duplicate indices.
        uids, gm, valid = _merge_sparse(g, ctx)
        # fused pallas path: gather + moment math + scatter in ONE call,
        # tables aliased in place (per-shard-local — sharded steps keep
        # the XLA branch below, whose scatter partitions under the mesh)
        if getattr(ctx, 'mesh', None) is None and \
                use_kernel(ctx, 'sparse_adagrad'):
            from ...ops.kernels import fused_sparse_adagrad
            p_out, m_out = fused_sparse_adagrad(p, m, uids, gm, valid,
                                                lr, eps)
            return {'ParamOut': p_out, 'MomentOut': m_out}
        vm = valid[:, None].astype(gm.dtype)
        m_rows = m[uids]
        m_new = m_rows + gm * gm
        p_delta = -lr * gm / (jnp.sqrt(m_new) + eps) * vm
        return {'ParamOut': p.at[uids].add(p_delta),
                'MomentOut': m.at[uids].add((m_new - m_rows) * vm)}
    g = data_of(g)
    m_out = m + g * g
    p_out = p - lr * g / (jnp.sqrt(m_out) + eps)
    return {'ParamOut': p_out, 'MomentOut': m_out}


@register('adam')
def _adam(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = ins['Grad'][0]
    m1 = data_of(ins['Moment1'][0])
    m2 = data_of(ins['Moment2'][0])
    b1p = data_of(ins['Beta1Pow'][0]).reshape(())
    b2p = data_of(ins['Beta2Pow'][0]).reshape(())
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    lr = _lr(ins) * jnp.sqrt(1 - b2p) / (1 - b1p)
    if isinstance(g, SparseRows):
        # lazy SelectedRows semantics (reference adam_op.h sparse branch):
        # only touched rows' moments decay/update; duplicates are merged
        # first so the nonlinear moment math sees each row's summed grad
        # once. Scattered as deltas — padding slots from the merge are
        # exact no-ops.
        uids, gm, valid = _merge_sparse(g, ctx)
        # fused pallas path (see adagrad above); lr is already
        # bias-corrected, exactly what the kernel applies per row
        if getattr(ctx, 'mesh', None) is None and \
                use_kernel(ctx, 'sparse_adam'):
            from ...ops.kernels import fused_sparse_adam
            p_out, m1_out, m2_out = fused_sparse_adam(
                p, m1, m2, uids, gm, valid, lr, b1, b2, eps)
            return {'ParamOut': p_out, 'Moment1Out': m1_out,
                    'Moment2Out': m2_out}
        vm = valid[:, None].astype(gm.dtype)
        m1_rows, m2_rows = m1[uids], m2[uids]
        m1_new = b1 * m1_rows + (1 - b1) * gm
        m2_new = b2 * m2_rows + (1 - b2) * gm * gm
        p_delta = -lr * m1_new / (jnp.sqrt(m2_new) + eps) * vm
        return {'ParamOut': p.at[uids].add(p_delta),
                'Moment1Out': m1.at[uids].add((m1_new - m1_rows) * vm),
                'Moment2Out': m2.at[uids].add((m2_new - m2_rows) * vm)}
    g = data_of(g)
    m1_out = b1 * m1 + (1 - b1) * g
    m2_out = b2 * m2 + (1 - b2) * g * g
    p_out = p - lr * m1_out / (jnp.sqrt(m2_out) + eps)
    return {'ParamOut': p_out, 'Moment1Out': m1_out, 'Moment2Out': m2_out}


@register('adam_beta_pow_update')
def _adam_beta_pow_update(ins, attrs, ctx):
    b1p = data_of(ins['Beta1Pow'][0])
    b2p = data_of(ins['Beta2Pow'][0])
    return {'Beta1PowOut': b1p * attrs.get('beta1', 0.9),
            'Beta2PowOut': b2p * attrs.get('beta2', 0.999)}


@register('adamax')
def _adamax(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    m = data_of(ins['Moment'][0])
    inf_norm = data_of(ins['InfNorm'][0])
    b1p = data_of(ins['Beta1Pow'][0]).reshape(())
    b1 = attrs.get('beta1', 0.9)
    b2 = attrs.get('beta2', 0.999)
    eps = attrs.get('epsilon', 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf_norm, jnp.abs(g))
    lr = _lr(ins) / (1 - b1p)
    p_out = p - lr * m_out / (inf_out + eps)
    return {'ParamOut': p_out, 'MomentOut': m_out, 'InfNormOut': inf_out}


@register('decayed_adagrad')
def _decayed_adagrad(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    m = data_of(ins['Moment'][0])
    decay = attrs.get('decay', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    m_out = decay * m + (1 - decay) * g * g
    p_out = p - _lr(ins) * g / (jnp.sqrt(m_out) + eps)
    return {'ParamOut': p_out, 'MomentOut': m_out}


@register('rmsprop')
def _rmsprop(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    ms = data_of(ins['MeanSquare'][0])
    mom = data_of(ins['Moment'][0])
    rho = attrs.get('decay', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    momentum = attrs.get('momentum', 0.0)
    ms_out = rho * ms + (1 - rho) * g * g
    mom_out = momentum * mom + _lr(ins) * g / jnp.sqrt(ms_out + eps)
    return {'ParamOut': p - mom_out, 'MomentOut': mom_out, 'MeanSquareOut': ms_out}


@register('adadelta')
def _adadelta(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    avg_sq_g = data_of(ins['AvgSquaredGrad'][0])
    avg_sq_u = data_of(ins['AvgSquaredUpdate'][0])
    rho = attrs.get('rho', 0.95)
    eps = attrs.get('epsilon', 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * g * g
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * update * update
    return {'ParamOut': p + update, 'AvgSquaredGradOut': g2,
            'AvgSquaredUpdateOut': u2}


@register('ftrl')
def _ftrl(ins, attrs, ctx):
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    sq = data_of(ins['SquaredAccumulator'][0])
    lin = data_of(ins['LinearAccumulator'][0])
    l1 = attrs.get('l1', 0.0)
    l2 = attrs.get('l2', 0.0)
    lr_power = attrs.get('lr_power', -0.5)
    lr = _lr(ins)
    new_sq = sq + g * g
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre = jnp.clip(new_lin, -l1, l1) - new_lin
    p_out = pre / denom
    return {'ParamOut': p_out, 'SquaredAccumOut': new_sq, 'LinearAccumOut': new_lin}
