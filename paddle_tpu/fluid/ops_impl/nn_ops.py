"""NN rules: conv/pool/norm/dropout/softmax/losses/metrics/image.

Parity: reference paddle/fluid/operators/{conv,pool,batch_norm,layer_norm,
dropout,softmax,cross_entropy,accuracy,auc,lrn,prelu,interpolate,...}_op.* —
cuDNN descriptors replaced by lax.conv_general_dilated / reduce_window, which
XLA tiles directly onto the TPU MXU.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import register, data_of, like, amp_cast


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


@register('conv2d')
def _conv2d(ins, attrs, ctx):
    """Conv in NCHW (reference operators/conv_op.cc) or NHWC
    (`data_format` attr — the layout XLA:TPU lays out natively, so NHWC
    feeds skip the compiler's transposes). Filter is always OIHW
    [out_c, in_c/groups, kh, kw] so weights are layout-portable."""
    x = data_of(ins['Input'][0])
    w = data_of(ins['Filter'][0])
    strides = _pair(attrs.get('strides', 1))
    pads = _pair(attrs.get('paddings', 0))
    dilations = _pair(attrs.get('dilations', 1))
    groups = attrs.get('groups', 1) or 1
    fmt = attrs.get('data_format', 'NCHW')
    in_dtype = x.dtype
    xc, wc = amp_cast(ctx, x, w.astype(x.dtype))
    # no preferred_element_type here: conv_general_dilated's transpose
    # (grad) rule feeds the f32 cotangent straight back into a bf16 conv
    # and trips a dtype mismatch; XLA:TPU accumulates bf16 convs in f32
    # internally regardless, so a plain bf16 conv + cast is equivalent
    out = lax.conv_general_dilated(
        xc, wc,
        window_strides=strides,
        padding=[(pads[0], pads[0]), (pads[1], pads[1])],
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=(fmt, 'OIHW', fmt))
    return {'Output': out.astype(in_dtype)}


@register('conv3d')
def _conv3d(ins, attrs, ctx):
    x = data_of(ins['Input'][0])
    w = data_of(ins['Filter'][0])
    strides = _pair(attrs.get('strides', 1), 3)
    pads = _pair(attrs.get('paddings', 0), 3)
    dilations = _pair(attrs.get('dilations', 1), 3)
    groups = attrs.get('groups', 1) or 1
    out = lax.conv_general_dilated(
        x, w.astype(x.dtype), strides,
        [(p, p) for p in pads], rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    return {'Output': out}


@register('conv2d_transpose')
def _conv2d_transpose(ins, attrs, ctx):
    """reference operators/conv_transpose_op.cc. Filter [in_c, out_c/g, kh, kw].
    Implemented as lhs-dilated conv (the XLA-native transposed conv)."""
    x = data_of(ins['Input'][0])
    w = data_of(ins['Filter'][0])
    strides = _pair(attrs.get('strides', 1))
    pads = _pair(attrs.get('paddings', 0))
    dilations = _pair(attrs.get('dilations', 1))
    groups = attrs.get('groups', 1) or 1
    kh = (w.shape[2] - 1) * dilations[0] + 1
    kw = (w.shape[3] - 1) * dilations[1] + 1
    # flip spatial dims, swap in/out channel axes -> OIHW for the fwd conv
    wt = jnp.flip(w, axis=(2, 3))
    if groups > 1:
        ci, co_g = w.shape[0], w.shape[1]
        wt = wt.reshape(groups, ci // groups, co_g, w.shape[2], w.shape[3])
        wt = jnp.swapaxes(wt, 1, 2).reshape(groups * co_g, ci // groups,
                                            w.shape[2], w.shape[3])
    else:
        wt = jnp.swapaxes(wt, 0, 1)
    out = lax.conv_general_dilated(
        x, wt.astype(x.dtype),
        window_strides=(1, 1),
        padding=[(kh - 1 - pads[0], kh - 1 - pads[0]),
                 (kw - 1 - pads[1], kw - 1 - pads[1])],
        lhs_dilation=strides,
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    return {'Output': out}


@register('conv3d_transpose')
def _conv3d_transpose(ins, attrs, ctx):
    x = data_of(ins['Input'][0])
    w = data_of(ins['Filter'][0])
    strides = _pair(attrs.get('strides', 1), 3)
    pads = _pair(attrs.get('paddings', 0), 3)
    dilations = _pair(attrs.get('dilations', 1), 3)
    ks = [(w.shape[2 + i] - 1) * dilations[i] + 1 for i in range(3)]
    wt = jnp.flip(w, axis=(2, 3, 4))
    wt = jnp.swapaxes(wt, 0, 1)
    out = lax.conv_general_dilated(
        x, wt.astype(x.dtype), (1, 1, 1),
        [(k - 1 - p, k - 1 - p) for k, p in zip(ks, pads)],
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    return {'Output': out}


def _pool(x, pool_type, ksize, strides, pads, global_pooling, exclusive=True,
          ceil_mode=False, channels_last=False):
    nd = len(ksize)
    if global_pooling:
        ksize = x.shape[1:1 + nd] if channels_last else x.shape[2:]
        pads = (0,) * nd
        strides = (1,) * nd

    def full(spatial, fill):
        # spatial window dims sit at [1..nd] for NHWC, [2..nd+1] for NCHW
        return ((fill,) + tuple(spatial) + (fill,)) if channels_last \
            else ((fill, fill) + tuple(spatial))

    window = full(ksize, 1)
    strides_full = full(strides, 1)
    pad_full = full(((p, p) for p in pads), (0, 0))
    if ceil_mode:
        pad_full = full(((p, p + s - 1) for p, s in zip(pads, strides)),
                        (0, 0))
    if pool_type == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides_full, pad_full)
    ssum = lax.reduce_window(x, 0.0, lax.add, window, strides_full, pad_full)
    if exclusive:
        # valid-count divisor: identical for every batch/channel, so count
        # over a singleton-batch/channel ones array and let broadcasting
        # expand it. Counting over full x.shape makes XLA constant-fold a
        # [B, C, H, W] reduce_window at COMPILE time — tens of seconds per
        # pool layer in a ResNet compile.
        shape1 = (1,) + tuple(x.shape[1:1 + nd]) + (1,) if channels_last \
            else (1, 1) + x.shape[2:]
        ones = jnp.ones(shape1, dtype=x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides_full,
                                pad_full)
        return ssum / cnt
    return ssum / float(np.prod(ksize))


@register('pool2d')
def _pool2d(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    out = _pool(x, attrs.get('pooling_type', 'max'),
                _pair(attrs['ksize']), _pair(attrs.get('strides', 1)),
                _pair(attrs.get('paddings', 0)),
                attrs.get('global_pooling', False),
                attrs.get('exclusive', True), attrs.get('ceil_mode', False),
                channels_last=attrs.get('data_format', 'NCHW') == 'NHWC')
    return {'Out': out}


@register('pool3d')
def _pool3d(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    out = _pool(x, attrs.get('pooling_type', 'max'),
                _pair(attrs['ksize'], 3), _pair(attrs.get('strides', 1), 3),
                _pair(attrs.get('paddings', 0), 3),
                attrs.get('global_pooling', False),
                attrs.get('exclusive', True), attrs.get('ceil_mode', False))
    return {'Out': out}


@register('batch_norm')
def _batch_norm(ins, attrs, ctx):
    """reference operators/batch_norm_op.cc. Train: batch stats + running
    update; test: running stats. NCHW or NHWC via data_layout."""
    x = data_of(ins['X'][0])
    scale = data_of(ins['Scale'][0])
    bias = data_of(ins['Bias'][0])
    mean = data_of(ins['Mean'][0])
    var = data_of(ins['Variance'][0])
    eps = attrs.get('epsilon', 1e-5)
    momentum = attrs.get('momentum', 0.9)
    is_test = attrs.get('is_test', False) or ctx.is_test
    layout = attrs.get('data_layout', 'NCHW')
    c_axis = 1 if layout == 'NCHW' else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = x.shape[c_axis]

    if is_test:
        use_mean, use_var = mean, var
        mean_out, var_out = mean, var
        saved_mean = mean
        saved_var = var
    else:
        xf = x.astype(jnp.float32)
        use_mean = jnp.mean(xf, axis=axes)
        use_var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(use_mean)
        mean_out = mean * momentum + use_mean * (1 - momentum)
        var_out = var * momentum + use_var * (1 - momentum)
        saved_mean = use_mean
        saved_var = use_var
    inv = lax.rsqrt(use_var + eps)
    y = (x - use_mean.reshape(bshape).astype(x.dtype)) * \
        (inv * scale).reshape(bshape).astype(x.dtype) + \
        bias.reshape(bshape).astype(x.dtype)
    return {'Y': y, 'MeanOut': mean_out, 'VarianceOut': var_out,
            'SavedMean': saved_mean, 'SavedVariance': saved_var}


@register('layer_norm')
def _layer_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    eps = attrs.get('epsilon', 1e-5)
    axis = attrs.get('begin_norm_axis', 1)
    red = tuple(range(axis, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=red, keepdims=True)
    y = (xf - mean) * lax.rsqrt(var + eps)
    if ins.get('Scale'):
        scale = data_of(ins['Scale'][0]).reshape((1,) * axis + x.shape[axis:])
        y = y * scale
    if ins.get('Bias'):
        bias = data_of(ins['Bias'][0]).reshape((1,) * axis + x.shape[axis:])
        y = y + bias
    return {'Y': like(ins['X'][0], y.astype(x.dtype)),
            'Mean': mean.reshape(x.shape[:axis]),
            'Variance': var.reshape(x.shape[:axis])}


@register('dropout')
def _dropout(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    p = attrs.get('dropout_prob', 0.5)
    is_test = attrs.get('is_test', False) or ctx.is_test
    if is_test:
        # downgrade_in_infer (default impl in the reference)
        return {'Out': like(ins['X'][0], x * (1.0 - p)), 'Mask': None}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    mask = keep.astype(x.dtype)
    return {'Out': like(ins['X'][0], x * mask), 'Mask': mask}


@register('softmax')
def _softmax(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': like(ins['X'][0], jax.nn.softmax(x, axis=-1))}


@register('cross_entropy')
def _cross_entropy(ins, attrs, ctx):
    """X: probs [N, C]; Label int64 [N, 1] (or probs if soft_label)."""
    x = data_of(ins['X'][0])
    label = data_of(ins['Label'][0])
    eps = 1e-8
    if attrs.get('soft_label', False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        li = label.astype(jnp.int32)
        if li.ndim == x.ndim:
            li = jnp.squeeze(li, -1)
        picked = jnp.take_along_axis(x, li[..., None], axis=-1)
        loss = -jnp.log(picked + eps)
    return {'Y': like(ins['X'][0], loss)}


@register('softmax_with_cross_entropy')
def _softmax_with_cross_entropy(ins, attrs, ctx):
    logits = data_of(ins['Logits'][0])
    label = data_of(ins['Label'][0])
    sm = jax.nn.softmax(logits, axis=-1)
    logp = jax.nn.log_softmax(logits, axis=-1)
    if attrs.get('soft_label', False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        li = label.astype(jnp.int32)
        if li.ndim == logits.ndim:
            li = jnp.squeeze(li, -1)
        loss = -jnp.take_along_axis(logp, li[..., None], axis=-1)
    return {'Softmax': sm, 'Loss': like(ins['Logits'][0], loss)}


@register('sigmoid_cross_entropy_with_logits')
def _sigmoid_xent(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    label = data_of(ins['Label'][0])
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {'Out': like(ins['X'][0], loss)}


@register('smooth_l1_loss')
def _smooth_l1(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    sigma = attrs.get('sigma', 1.0)
    s2 = sigma * sigma
    d = x - y
    if ins.get('InsideWeight'):
        d = d * data_of(ins['InsideWeight'][0])
    ad = jnp.abs(d)
    loss = jnp.where(ad < 1.0 / s2, 0.5 * s2 * d * d, ad - 0.5 / s2)
    if ins.get('OutsideWeight'):
        loss = loss * data_of(ins['OutsideWeight'][0])
    out = jnp.sum(loss.reshape(loss.shape[0], -1), axis=1, keepdims=True)
    return {'Out': out, 'Diff': d}


@register('rank_loss')
def _rank_loss(ins, attrs, ctx):
    label = data_of(ins['Label'][0])
    left = data_of(ins['Left'][0])
    right = data_of(ins['Right'][0])
    d = left - right
    out = jnp.log1p(jnp.exp(d)) - label * d
    return {'Out': out}


@register('dice_loss')
def _dice_loss(ins, attrs, ctx):
    x = data_of(ins['X'][0])  # probs
    label = data_of(ins['Label'][0]).astype(x.dtype)
    eps = attrs.get('epsilon', 1e-5)
    red = tuple(range(1, x.ndim))
    inter = 2.0 * jnp.sum(x * label, axis=red)
    union = jnp.sum(x, axis=red) + jnp.sum(label, axis=red)
    return {'Out': jnp.mean(1.0 - (inter + eps) / (union + eps))}


@register('huber_loss')
def _huber_loss(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    delta = attrs.get('delta', 1.0)
    d = jnp.abs(y - x)
    loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return {'Out': loss, 'Residual': y - x}


@register('accuracy')
def _accuracy(ins, attrs, ctx):
    """inputs: Out (topk values), Indices (topk ids), Label. reference
    operators/accuracy_op.cu."""
    idx = data_of(ins['Indices'][0]).astype(jnp.int64)
    label = data_of(ins['Label'][0]).astype(jnp.int64)
    if label.ndim < idx.ndim:
        label = label[..., None]
    correct = jnp.any(idx == label, axis=-1)
    num_correct = jnp.sum(correct.astype(jnp.int32))
    total = correct.size
    # shape [1] like the reference (accuracy_op InferShape dims {1}):
    # verbatim scripts index the fetched value as acc_np[0]
    acc = (num_correct.astype(jnp.float32) / float(total)).reshape(1)
    return {'Accuracy': acc, 'Correct': num_correct,
            'Total': jnp.asarray(total, dtype=jnp.int32)}


@register('auc')
def _auc(ins, attrs, ctx):
    """Streaming AUC over persistable confusion buckets (reference
    operators/auc_op.cc). States: StatPos/StatNeg histograms."""
    probs = data_of(ins['Predict'][0])
    label = data_of(ins['Label'][0]).reshape(-1)
    stat_pos = data_of(ins['StatPos'][0])
    stat_neg = data_of(ins['StatNeg'][0])
    num_t = stat_pos.shape[0]
    p1 = probs[:, 1] if probs.ndim == 2 and probs.shape[1] >= 2 else probs.reshape(-1)
    bucket = jnp.clip((p1 * num_t).astype(jnp.int32), 0, num_t - 1)
    is_pos = (label > 0)
    pos_hist = jnp.zeros((num_t,), jnp.int64).at[bucket].add(is_pos.astype(jnp.int64))
    neg_hist = jnp.zeros((num_t,), jnp.int64).at[bucket].add((~is_pos).astype(jnp.int64))
    new_pos = stat_pos + pos_hist
    new_neg = stat_neg + neg_hist
    # AUC = (sum over thresholds of neg_below * pos_at + .5*neg_at*pos_at)/(P*N)
    pos = new_pos.astype(jnp.float64)
    neg = new_neg.astype(jnp.float64)
    tot_pos = jnp.cumsum(pos)
    tot_neg = jnp.cumsum(neg)
    area = jnp.sum((tot_neg - neg * 0.5) * pos)
    denom = jnp.maximum(tot_pos[-1] * tot_neg[-1], 1.0)
    auc = (area / denom).astype(jnp.float32)
    return {'AUC': auc, 'StatPosOut': new_pos, 'StatNegOut': new_neg}


@register('lrn')
def _lrn(ins, attrs, ctx):
    x = data_of(ins['X'][0])  # NCHW
    n = attrs.get('n', 5)
    k = attrs.get('k', 2.0)
    alpha = attrs.get('alpha', 1e-4)
    beta = attrs.get('beta', 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {'Out': x / jnp.power(mid, beta), 'MidOut': mid}


@register('prelu')
def _prelu(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    alpha = data_of(ins['Alpha'][0])
    mode = attrs.get('mode', 'all')
    if mode == 'all':
        a = alpha.reshape(())
    elif mode == 'channel':
        a = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    else:
        a = alpha.reshape((1,) + x.shape[1:])
    return {'Out': jnp.where(x >= 0, x, a * x)}


def _resize(x, out_h, out_w, method):
    n, c, h, w = x.shape
    return jax.image.resize(x, (n, c, out_h, out_w), method=method)


@register('bilinear_interp')
def _bilinear_interp(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    if ins.get('OutSize'):
        raise ValueError(
            "image_resize with a runtime OutSize tensor is data-dependent "
            "shape — unsupported under XLA; pass a static out_shape list")
    out_h, out_w = attrs['out_h'], attrs['out_w']
    return {'Out': _resize(x, out_h, out_w, 'bilinear')}


@register('nearest_interp')
def _nearest_interp(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    out_h, out_w = attrs['out_h'], attrs['out_w']
    return {'Out': _resize(x, out_h, out_w, 'nearest')}


@register('roi_pool')
def _roi_pool(ins, attrs, ctx):
    """reference operators/roi_pool_op.cc. ROIs: [R, 4] (x1,y1,x2,y2) with
    batch id in RoisLod-free single-image mode; here ROIs carry batch index
    via first column when 5-wide."""
    x = data_of(ins['X'][0])
    rois = data_of(ins['ROIs'][0])
    ph = attrs['pooled_height']
    pw = attrs['pooled_width']
    scale = attrs.get('spatial_scale', 1.0)
    n, c, h, w = x.shape

    if rois.shape[-1] == 5:
        batch_ids = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    else:
        batch_ids = jnp.zeros((rois.shape[0],), jnp.int32)
        boxes = rois

    def pool_one(bid, box):
        img = x[bid]
        x1 = jnp.round(box[0] * scale).astype(jnp.int32)
        y1 = jnp.round(box[1] * scale).astype(jnp.int32)
        x2 = jnp.round(box[2] * scale).astype(jnp.int32)
        y2 = jnp.round(box[3] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        rw = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        # bin index of each pixel, -1 if outside roi
        ybin = jnp.floor((ys - y1).astype(jnp.float32) / (rh / ph)).astype(jnp.int32)
        xbin = jnp.floor((xs - x1).astype(jnp.float32) / (rw / pw)).astype(jnp.int32)
        yvalid = (ys >= y1) & (ys <= y2)
        xvalid = (xs >= x1) & (xs <= x2)
        ybin = jnp.clip(ybin, 0, ph - 1)
        xbin = jnp.clip(xbin, 0, pw - 1)
        neg = jnp.full(img.shape, -jnp.inf, img.dtype)
        masked = jnp.where(yvalid[None, :, None] & xvalid[None, None, :], img, neg)
        out = jnp.full((c, ph, pw), -jnp.inf, img.dtype)
        out = out.at[:, ybin[:, None], xbin[None, :]].max(masked)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    out = jax.vmap(pool_one)(batch_ids, boxes)
    return {'Out': out, 'Argmax': None}


@register('mean_iou')
def _mean_iou(ins, attrs, ctx):
    pred = data_of(ins['Predictions'][0]).reshape(-1).astype(jnp.int32)
    label = data_of(ins['Labels'][0]).reshape(-1).astype(jnp.int32)
    num_classes = attrs['num_classes']
    idx = label * num_classes + pred
    cm = jnp.zeros((num_classes * num_classes,), jnp.float32).at[idx].add(1.0)
    cm = cm.reshape(num_classes, num_classes)
    inter = jnp.diag(cm)
    union = cm.sum(0) + cm.sum(1) - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    mean_iou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    return {'OutMeanIou': mean_iou, 'OutWrong': jnp.sum(cm, axis=1) - inter,
            'OutCorrect': inter}


@register('im2sequence')
def _im2sequence(ins, attrs, ctx):
    """reference operators/im2sequence_op.cc: NCHW image -> sequence of
    flattened patches [N, out_h*out_w, C*kh*kw] (dense-padded layout)."""
    x = data_of(ins['X'][0])
    kh, kw = _pair(attrs['kernels'])
    sh, sw = _pair(attrs.get('strides', 1))
    p = attrs.get('paddings', [0, 0, 0, 0])
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2] if len(p) > 2 else p[0]),
                     (p[1] if len(p) > 1 else p[0], p[3] if len(p) > 3 else p[0])))
    patches = lax.conv_general_dilated_patches(
        xp, (kh, kw), (sh, sw), 'VALID',
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))  # [N, C*kh*kw, oh, ow]
    ckk = patches.shape[1]
    seq = patches.reshape(n, ckk, -1).transpose(0, 2, 1)  # [N, oh*ow, C*kh*kw]
    from ..lowering import SeqValue
    lengths = jnp.full((n,), seq.shape[1], jnp.int32)
    return {'Out': SeqValue(seq, lengths)}


@register('flash_attention')
def _flash_attention(ins, attrs, ctx):
    """Fused attention: pallas flash kernel on TPU, XLA chain elsewhere.
    Replaces the reference's matmul+softmax+matmul op sequence — see
    paddle_tpu/ops/flash_attention.py for the kernel."""
    from ... import ops as tpu_ops
    q = data_of(ins['Q'][0])
    k = data_of(ins['K'][0])
    v = data_of(ins['V'][0])
    kb = ins.get('KeyBias')
    kb = data_of(kb[0]) if kb else None
    if kb is not None:
        kb = kb.reshape(kb.shape[0], kb.shape[-1])
    scale = attrs.get('scale', -1.0)
    scale = None if scale is None or scale < 0 else float(scale)
    causal = bool(attrs.get('causal', False))
    q, k, v = amp_cast(ctx, q, k, v)
    mesh = getattr(ctx, 'mesh', None)
    if mesh is not None and 'sp' in getattr(mesh, 'shape', {}):
        # sequence-parallel mesh (SequenceParallelTranspiler): the O(T^2)
        # attention distributes over the sp axis as a ppermute ring; each
        # device holds O(T/sp) keys (flash blocks on TPU, dense on CPU)
        sp = mesh.shape['sp']
        strategy = attrs.get('sp_strategy', 'ring')
        if 'sp' in getattr(ctx, 'manual_axes', ()):
            # already INSIDE a shard_map manual over sp (the pipeline
            # region): q/k/v arrive sequence-LOCAL [B, H, T/sp, D]; call
            # the per-shard collective bodies directly — nesting another
            # shard_map here would be invalid
            if strategy == 'ulysses':
                from ...parallel.ulysses import ulysses_attention
                out = ulysses_attention(q, k, v, 'sp', key_bias=kb,
                                        causal=causal, sm_scale=scale)
            else:
                from ...parallel.ring_attention import ring_attention
                out = ring_attention(q, k, v, 'sp', key_bias=kb,
                                     causal=causal, sm_scale=scale)
            return {'Out': out}
        if q.shape[2] % sp or k.shape[2] % sp:
            raise ValueError(
                'sequence parallelism: the sp mesh axis size %d must '
                'divide the seq lens %d/%d'
                % (sp, q.shape[2], k.shape[2]))
        if strategy == 'ulysses':
            from ...parallel.ulysses import ulysses_self_attention
            out = ulysses_self_attention(mesh, q, k, v, axis='sp',
                                         key_bias=kb, causal=causal,
                                         sm_scale=scale)
        else:
            from ...parallel.ring_attention import ring_self_attention
            out = ring_self_attention(mesh, q, k, v, axis='sp', key_bias=kb,
                                      causal=causal, sm_scale=scale)
    elif ctx.platform in ('tpu', 'axon'):
        out = tpu_ops.flash_attention(q, k, v, key_bias=kb, causal=causal,
                                      sm_scale=scale, interpret=False)
    else:
        out = tpu_ops.reference_attention(q, k, v, key_bias=kb,
                                          causal=causal, sm_scale=scale)
    return {'Out': out}
