"""Verbatim LoD beam search: the book's While-loop decoder idiom.

Reference: operators/beam_search_op.cc (SelectTopBeamSizeItems / ToMap /
PruneEndBeams) + operators/beam_search_decode_op.h (Backtrace +
ConvertSentenceVectorToLodTensor), driven by
tests/book/test_machine_translation.py:decode_main. There the number of
live beam rows per source changes every While iteration and lives in the
2-level LoD of selected_ids/scores. XLA needs static shapes, so this
module runs the SAME algorithm at fixed CAPACITY:

  - every step tensor is a SeqValue with data [B*K, ...] — source s owns
    the row block [s*K, (s+1)*K), its live rows compacted to the front;
  - `lengths` (int32[B*K]) is the reference's lod[1] at capacity: entry
    s*K + p = number of selected children of parent group p (a row of the
    PREVIOUS step); dead slots hold 0;
  - `outer_lengths[0]` (int32[B]) is lod[0]: parent groups per source.

The capacity form is produced by the While capacity-widening pass
(ops_impl/block_ops.py:_widen_carry_to_body) from the narrower pre-loop
feeds, and consumed/emitted by the beam_search / sequence_expand /
lod_reset / is_empty branches below. `beam_search_decode` backtraces the
LoDTensorArrays exactly like the reference's host walk, on device.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..lowering import SeqValue, ArrayValue, data_of

NEG = -1e30


def is_beam_form(v):
    """Capacity-form 2-level SeqValue, detected by the EXPLICIT beam_cap
    flag (static pytree aux) that only normalize_capacity, the While
    capacity-widening pass, and the beam ops themselves set. The old
    shape heuristic (outer vector shorter than the row dim + divisibility)
    misrouted ordinary 2-level data with uniform group counts — e.g. 2
    sources x 3 groups = 6 rows — onto the beam path, silently producing
    wrong values (round-5 ADVICE, medium). The structural conditions are
    kept as an AND so a mis-propagated flag on a value that cannot be
    capacity form still falls through to the ordinary path."""
    return bool(isinstance(v, SeqValue) and v.beam_cap and v.outer_lengths
                and v.outer_lengths[0].shape[0] != v.data.shape[0]
                and v.data.shape[0] % v.outer_lengths[0].shape[0] == 0)


def blocks(v):
    """(B, K) of a capacity-form SeqValue."""
    b = v.outer_lengths[0].shape[0]
    return b, v.data.shape[0] // b


def rows_live(v):
    """[B] live row count per source block = sum of lod[1] lengths."""
    b, k = blocks(v)
    return v.lengths.reshape(b, k).sum(axis=1)


def _compact_order(sel, key):
    """Per-source compaction: argsort putting selected entries first in
    `key` order. sel/key: [B, N]. Returns indices [B, N]."""
    n = sel.shape[1]
    rank = jnp.where(sel, key, n * n + key)
    return jnp.argsort(rank, axis=1)


def _rows_per_source_narrow(v):
    """Rows per source of a NARROW (pre-capacity) 2-level SeqValue: level-0
    lengths count level-1 groups, and in the decode idiom each group is one
    row (data [rows, 1, ...] or [rows, ...])."""
    return v.outer_lengths[0].astype(jnp.int32)


def normalize_capacity(pre_ids, pre_scores, ids, scores, beam_size):
    """Bring a beam step's inputs to capacity form [B*K, ...].

    The While capacity-widening pass (block_ops) normally does this before
    the loop ever runs; this in-rule fallback serves its OWN abstract
    probe (the first eval_shape of the body sees the narrow pre-loop
    shapes) and direct eager calls on feed-shaped values."""
    if is_beam_form(pre_scores):
        return pre_ids, pre_scores, data_of(ids), data_of(scores)
    B = pre_scores.outer_lengths[0].shape[0]
    K = int(beam_size)
    rows = _rows_per_source_narrow(pre_scores)
    n_rows = data_of(pre_scores).shape[0]
    # source of each narrow row + its position within the source block
    ends = jnp.cumsum(rows)
    src = jnp.searchsorted(ends, jnp.arange(n_rows), side='right')
    src = jnp.minimum(src, B - 1)
    pos = jnp.arange(n_rows) - jnp.where(src > 0, ends[src - 1], 0)
    dest = src * K + pos                                  # [n_rows]

    def scatter(flat, fill=0):
        flat = data_of(flat)
        if flat.ndim >= 2 and flat.shape[1] == 1 and flat.ndim > 2:
            flat = flat[:, 0]                             # drop pad-time dim
        out = jnp.full((B * K,) + flat.shape[1:], fill, flat.dtype)
        return out.at[dest].set(flat)

    l1 = jnp.zeros((B * K,), jnp.int32).at[dest].set(1)
    mk = lambda v: SeqValue(scatter(v), l1, (rows,), beam_cap=True)
    return (mk(pre_ids), mk(pre_scores), scatter(ids), scatter(scores))


def beam_search_step(pre_ids, pre_scores, ids, scores, beam_size, end_id):
    """One reference beam_search step on capacity-form values.

    pre_ids/pre_scores: SeqValue [B*K, 1]; ids/scores: [B*K, topk] dense.
    Returns (selected_ids SeqValue, selected_scores SeqValue,
    parent_rows int32[B*K] global parent row per output row, -1 dead).
    """
    K = int(beam_size)
    B, Kcap = blocks(pre_scores)
    R = B * Kcap
    pid = data_of(pre_ids).reshape(R).astype(jnp.int32)
    psc = data_of(pre_scores).reshape(R).astype(jnp.float32)
    cid = data_of(ids).reshape(R, -1).astype(jnp.int32)
    csc = data_of(scores).reshape(R, -1).astype(jnp.float32)
    topk = cid.shape[1]

    live = ((jnp.arange(R) % Kcap).reshape(B, Kcap)
            < rows_live(pre_scores)[:, None]).reshape(R)
    ended = live & (pid == end_id)

    # candidate table [R, topk]: ended rows contribute ONE candidate
    # (end_id, pre_score) in slot 0 (reference NextItemSet); dead rows
    # contribute none
    slot0 = jnp.arange(topk)[None, :] == 0
    cand_sc = jnp.where(ended[:, None],
                        jnp.where(slot0, psc[:, None], NEG), csc)
    cand_id = jnp.where(ended[:, None], end_id, cid)
    cand_sc = jnp.where(live[:, None], cand_sc, NEG)

    # top beam_size per SOURCE over its Kcap*topk candidates
    flat_sc = cand_sc.reshape(B, Kcap * topk)
    top_sc, top_pos = lax.top_k(flat_sc, K)              # [B, K]
    sel_valid = top_sc > NEG / 2
    # PruneEndBeams: a source whose LIVE rows are all ended selects only
    # end-repeats -> emits nothing further (reference clears its items)
    finished = (rows_live(pre_scores) > 0) & \
        ((~live | ended).reshape(B, Kcap).all(axis=1))
    sel_valid = sel_valid & ~finished[:, None]

    # group output rows by parent row ascending, then candidate slot
    # ascending (reference writes items per offset in encounter order)
    sel_mask = jnp.zeros((B, Kcap * topk), bool)
    sel_mask = sel_mask.at[jnp.arange(B)[:, None], top_pos].set(sel_valid)
    order = _compact_order(sel_mask, jnp.arange(Kcap * topk)[None, :])
    ordered_pos = order[:, :Kcap]                        # [B, Kcap]
    ordered_ok = jnp.take_along_axis(sel_mask, ordered_pos, axis=1)
    parent_local = ordered_pos // topk                   # [B, Kcap]
    out_id = jnp.take_along_axis(cand_id.reshape(B, Kcap * topk),
                                 ordered_pos, axis=1)
    out_sc = jnp.take_along_axis(cand_sc.reshape(B, Kcap * topk),
                                 ordered_pos, axis=1)
    out_id = jnp.where(ordered_ok, out_id, 0)
    out_sc = jnp.where(ordered_ok, out_sc, 0.0)

    # lod[1]: children per parent slot; lod[0]: parent groups per source
    # (the input's row count — reference copies high_level verbatim)
    l1 = jax.vmap(lambda pl, ok: jnp.zeros(
        (Kcap,), jnp.int32).at[pl].add(ok.astype(jnp.int32)))(
            parent_local, ordered_ok)
    l0 = rows_live(pre_scores).astype(jnp.int32)

    parent_rows = jnp.where(
        ordered_ok,
        parent_local + (jnp.arange(B) * Kcap)[:, None], -1)
    sel_ids = SeqValue(out_id.reshape(R, 1).astype(jnp.int64),
                       l1.reshape(R), (l0,), beam_cap=True)
    sel_scores = SeqValue(out_sc.reshape(R, 1), l1.reshape(R), (l0,),
                          beam_cap=True)
    return sel_ids, sel_scores, parent_rows.reshape(R)


def sequence_expand_beam(x, y):
    """x row for parent group p of source s sits at x.data[s*K + p] (the
    previous step's children ARE this step's parent groups); output row
    (s, child c) copies x[parent_of(c)] (reference sequence_expand over
    the 2-level LoD)."""
    B, Kcap = blocks(y)
    xd = data_of(x)
    if xd.ndim > 2 and xd.shape[1] == 1:
        xd = xd[:, 0]
    # parent group of each child row: child rows are compacted per source
    # in parent order, so parent(c) = searchsorted(cumsum(l1), c)
    l1 = y.lengths.reshape(B, Kcap)
    ends = jnp.cumsum(l1, axis=1)                        # [B, Kcap]
    child_pos = jnp.arange(Kcap)[None, :]
    parent = jax.vmap(
        lambda e: jnp.searchsorted(e, child_pos[0], side='right'))(ends)
    parent = jnp.minimum(parent, Kcap - 1)
    rows = parent + jnp.arange(B)[:, None] * Kcap        # [B, Kcap] global
    out = xd[rows.reshape(-1)]
    # emit [rows, 1, ...]: each output row is a one-token level-1 group,
    # and downstream fc ops were shape-inferred for the padded 3-D layout
    return SeqValue(out[:, None], y.lengths, y.outer_lengths,
                    beam_cap=True)


def is_empty_beam(v):
    return (rows_live(v).sum() == 0).reshape(())


def beam_search_decode_arrays(ids_arr, scores_arr, beam_size, end_id):
    """Backtrace the step arrays into sentences (reference Backtrace +
    ConvertSentenceVectorToLodTensor with reverse=true sort_by_score=true,
    the op defaults — hypotheses per source ordered by accumulated score).

    Returns (sentence_ids SeqValue [B*K, T_cap] int64, sentence_scores
    SeqValue same shape float32): lengths = tokens per hypothesis, outer =
    hypotheses per source.
    """
    data_ids = ids_arr.buffer[0]            # [T_cap, R, 1]
    lens = ids_arr.buffer[1]                # [T_cap, R]
    data_sc = scores_arr.buffer[0]
    T_cap, R = lens.shape
    n_src = ids_arr.buffer[2].shape[1]
    B, Kcap = n_src, R // n_src
    T_live = ids_arr.length                  # traced scalar

    l1 = lens.reshape(T_cap, B, Kcap)
    child_cnt = l1.sum(axis=2)               # [T_cap, B] live children
    step_ok = (jnp.arange(T_cap)[:, None] < T_live) & (child_cnt > 0)
    # seed step per source: the LAST step with any children (a source
    # finished+pruned earlier seeds at its own last nonempty step —
    # reference's "be finished and pruned at this step" branch)
    t_seed = jnp.where(step_ok, jnp.arange(T_cap)[:, None], -1).max(0)

    ends = jnp.cumsum(l1, axis=2)            # [T_cap, B, Kcap]

    def parent_of(t, child):                 # child [B, K] local indices
        e = ends[t]                          # [B, Kcap]
        return jax.vmap(
            lambda ee, cc: jnp.minimum(
                jnp.searchsorted(ee, cc, side='right'), Kcap - 1))(e, child)

    n_hyp = jnp.take_along_axis(
        child_cnt, jnp.maximum(t_seed, 0)[None, :], axis=0)[0]
    n_hyp = jnp.minimum(n_hyp, Kcap)

    hyp = jnp.broadcast_to(jnp.arange(Kcap)[None, :], (B, Kcap))

    def step_back(carry, t):
        ptr, started = carry                 # [B, K] row ptr, bool active
        start_now = (t == t_seed)[:, None] & \
            (hyp < n_hyp[:, None])
        ptr = jnp.where(start_now, hyp, ptr)
        started = started | start_now
        gidx = (jnp.arange(B)[:, None] * Kcap + ptr).reshape(-1)
        tok = data_ids[t].reshape(R)[gidx].reshape(B, Kcap)
        sc = data_sc[t].reshape(R)[gidx].reshape(B, Kcap)
        valid = started
        new_ptr = jnp.where(started, parent_of(t, ptr), ptr)
        return (new_ptr, started), (tok, sc, valid)

    (_, _), (toks, scs, valids) = lax.scan(
        step_back, (jnp.zeros((B, Kcap), jnp.int32),
                    jnp.zeros((B, Kcap), bool)),
        jnp.arange(T_cap - 1, -1, -1))
    # toks: [T_cap, B, K] backward order (seed first)

    # "skip redundant end tokens": drop end_id unless it is the first
    # (seed-position) token of the hypothesis
    first = jnp.cumsum(valids.astype(jnp.int32), axis=0) == 1
    keep = valids & (first | (toks.astype(jnp.int32) != end_id))

    # forward order with left-compaction per hypothesis
    def fix_one(tk, sc, kp):
        # tk/sc/kp: [T_cap] backward; output forward-compacted [T_cap]
        n = kp.sum()
        order = jnp.argsort(jnp.where(kp, -jnp.arange(T_cap), T_cap))
        return tk[order], sc[order], n

    flat = lambda a: jnp.moveaxis(a, 0, -1).reshape(B * Kcap, T_cap)
    tok_f, sc_f, nt = jax.vmap(fix_one)(flat(toks), flat(scs), flat(keep))
    hyp_valid = (jnp.arange(Kcap)[None, :] < n_hyp[:, None]).reshape(-1)
    nt = jnp.where(hyp_valid, nt, 0).astype(jnp.int32)

    # sort_by_score (reference ConvertSentenceVectorToLodTensor default):
    # hypotheses within a source ordered by their accumulated score — the
    # seed (last-step) score, since beam scores accumulate — descending;
    # ties keep beam-slot order (argsort is stable)
    seed_sc = (scs * first.astype(scs.dtype)).sum(0)      # [B, Kcap]
    seed_key = jnp.where(hyp < n_hyp[:, None], -seed_sc, jnp.inf)
    perm = jnp.argsort(seed_key, axis=1)
    rows = (jnp.arange(B)[:, None] * Kcap + perm).reshape(-1)
    tok_f, sc_f, nt = tok_f[rows], sc_f[rows], nt[rows]

    sent_ids = SeqValue(tok_f.astype(jnp.int64), nt,
                        (n_hyp.astype(jnp.int32),), beam_cap=True)
    sent_scores = SeqValue(sc_f.astype(jnp.float32), nt,
                           (n_hyp.astype(jnp.int32),), beam_cap=True)
    return sent_ids, sent_scores


# ---------------------------------------------------------------------------
# step-form decode: the whole-sequence While body factored into ONE reusable
# beam step (serving/decode.py's continuous-batching engine drives it slot by
# slot; sampled_ops' attention_lstm_beam_decode scans it whole-sequence — one
# definition, so the two paths are fetch-equivalent by construction)
# ---------------------------------------------------------------------------

def beam_init_carry(rows, beam, hidden, start_id, dtype=jnp.float32):
    """Fresh decode carry for `rows` sources at beam width `beam`, flat
    [rows*beam, ...] layout: zero LSTM state, start_id everywhere, and only
    beam 0 live in the accumulated scores so the first top-k doesn't pick
    `beam` copies of the same candidate."""
    n = rows * beam
    neg = jnp.finfo(jnp.float32).min
    return (jnp.zeros((n, hidden), dtype),
            jnp.zeros((n, hidden), dtype),
            jnp.full((n,), start_id, jnp.int32),
            jnp.where(jnp.arange(n) % beam == 0, 0.0, neg),
            jnp.zeros((n,), bool))


def attention_beam_step(params, enc_t, mask_t, carry, beam, end_id,
                        attend=None):
    """One attend -> LSTM cell -> project -> joint top-k -> reorder beam
    step on flat [B*beam, ...] rows (every row is independent: no
    cross-row reduction ever mixes two sources, which is what lets the
    continuous-batching engine pack unrelated slots into one module and
    mask the dead ones).

    params: (w_dec [E+D,4H], u_dec [H,4H], b_dec, w_q [H,D], w_emb [V,E],
    w_out [H,V], b_out); enc_t [B*beam, S, D] (source rows repeated per
    beam); mask_t [B*beam, S] 1/0; carry = (h, c, prev_ids, acc, fin) as
    built by beam_init_carry. Returns (carry', (sel_ids [B, beam],
    parent [B, beam] local beam index, top_scores [B, beam])).

    `attend`: optional q [Bb, D] -> ctx [Bb, D] override — the paged
    decode rules pass the fused paged_attention kernel here (which reads
    the encoder PAGES directly, so they pass enc_t/mask_t as None and
    skip materializing the repeated tensors). None keeps the inline
    attend math below, byte-identical to the pre-kernel lowering."""
    w_dec, u_dec, b_dec, w_q, w_emb, w_out, b_out = params
    hp, cp, prev_ids, acc, fin = carry
    Bb = hp.shape[0]
    B = Bb // beam
    V = w_out.shape[1]
    neg = jnp.finfo(jnp.float32).min

    x_t = jnp.take(w_emb, prev_ids, axis=0)          # [Bb, E]
    q = hp @ w_q
    if attend is not None:
        ctx_vec = attend(q)
    else:
        scores = jnp.einsum('bd,bsd->bs', q, enc_t)
        scores = jnp.where(mask_t > 0, scores, neg)
        alpha = jax.nn.softmax(scores, axis=-1)
        ctx_vec = jnp.einsum('bs,bsd->bd', alpha, enc_t)
    g = jnp.concatenate([x_t, ctx_vec], -1) @ w_dec + hp @ u_dec + b_dec
    gi, gf, gc, go = jnp.split(g, 4, axis=-1)
    c_new = jax.nn.sigmoid(gf) * cp + \
        jax.nn.sigmoid(gi) * jnp.tanh(gc)
    h_new = jax.nn.sigmoid(go) * jnp.tanh(c_new)

    logp = jax.nn.log_softmax(
        (h_new @ w_out + b_out).astype(jnp.float32), axis=-1)
    cand = acc[:, None] + logp                        # [Bb, V]
    # finished beams: single end_id candidate carrying score forward
    onehot_end = (jnp.arange(V)[None, :] == end_id)
    cand = jnp.where(fin[:, None],
                     jnp.where(onehot_end, acc[:, None], neg), cand)

    flat = cand.reshape(B, beam * V)
    top_scores, top_pos = lax.top_k(flat, beam)       # [B, beam]
    parent = (top_pos // V).astype(jnp.int32)         # [B, beam]
    sel_ids = (top_pos % V).astype(jnp.int32)
    gidx = (parent + beam * jnp.arange(B)[:, None]).reshape(Bb)

    h_new = jnp.take(h_new, gidx, axis=0)
    c_new = jnp.take(c_new, gidx, axis=0)
    new_ids = sel_ids.reshape(Bb)
    new_acc = top_scores.reshape(Bb)
    new_fin = jnp.take(fin, gidx) | (new_ids == end_id)
    return (h_new, c_new, new_ids, new_acc, new_fin), \
        (sel_ids, parent, top_scores)


def greedy_attend_cell(params, enc, mask, h, c, tok, attend=None):
    """One attend -> LSTM cell -> project step for [B] independent rows
    with NO beam dimension — the draft model's proposal step in
    speculative decoding (sampled_ops.attention_lstm_spec_decode_step)
    and the reference the verify phase's split-projection restructuring
    is measured against. Same cell math as attention_beam_step at
    beam=1, minus the top-k/reorder bookkeeping.

    params: the WEIGHT_KEYS tuple (w_dec [E+D,4H], u_dec [H,4H], b_dec,
    w_q [H,D], w_emb [V,E], w_out [H,V], b_out); enc [B, S, D];
    mask [B, S] 1/0; h/c [B, H]; tok [B] int32.
    Returns (h2, c2, logits [B, V] float32).

    `attend`: optional q [B, D] -> ctx [B, D] override (see
    attention_beam_step) — with it set, enc/mask may be None."""
    w_dec, u_dec, b_dec, w_q, w_emb, w_out, b_out = params
    neg = jnp.finfo(jnp.float32).min
    x = jnp.take(w_emb, tok, axis=0)
    q = h @ w_q
    if attend is not None:
        ctx = attend(q)
    else:
        scores = jnp.einsum('bd,bsd->bs', q, enc)
        scores = jnp.where(mask > 0, scores, neg)
        alpha = jax.nn.softmax(scores, axis=-1)
        ctx = jnp.einsum('bs,bsd->bd', alpha, enc)
    g = jnp.concatenate([x, ctx], -1) @ w_dec + h @ u_dec + b_dec
    gi, gf, gc, go = jnp.split(g, 4, axis=-1)
    c2 = jax.nn.sigmoid(gf) * c + jax.nn.sigmoid(gi) * jnp.tanh(gc)
    h2 = jax.nn.sigmoid(go) * jnp.tanh(c2)
    logits = (h2 @ w_out + b_out).astype(jnp.float32)
    return h2, c2, logits


def backtrace_beams(ids_seq, par_seq):
    """Host-side backtrace of one source's per-step beams — the exact
    numpy transcription of the whole-sequence op's in-graph `back` scan
    (sampled_ops._attention_lstm_beam_decode), run per slot by the
    continuous engine when the slot releases.

    ids_seq/par_seq: [L, beam] selected token / local parent per step.
    Returns int token matrix [beam, L] in forward order. Steps past the
    point where every beam finished contribute end_id tokens and identity
    parents (that is literally what the fused scan emits there — acc is
    already sorted descending by construction, so its tail top-k is the
    identity permutation), so truncating at release and padding with
    end_id reproduces the lockstep output bit for bit."""
    ids_seq = np.asarray(ids_seq)
    par_seq = np.asarray(par_seq)
    L, beam = ids_seq.shape
    ptr = np.arange(beam)
    toks = np.empty((L, beam), ids_seq.dtype)
    for t in range(L - 1, -1, -1):
        toks[t] = ids_seq[t][ptr]
        ptr = par_seq[t][ptr]
    return toks.T
