"""Long-tail op rules: reference operators that exist only at the C++
level (each has a reference unittest test_<op>_op.py but no v0.14 python
layer). Registered here so `layer_function_generator.generate_layer_fn`
— the reference's own mechanism for exposing registered ops — reaches
them, plus the handful of layers/ops.py wrappers.

Parity: paddle/fluid/operators/{sign,cum,l1_norm,squared_l2_norm,
squared_l2_distance,minus,fill,fill_zeros_like,norm,log_loss,hinge_loss,
margin_rank_loss,modified_huber_loss,sampling_id,conv_shift,
bilinear_tensor_product,sequence_concat,sequence_slice,sequence_erase,
proximal_gd,proximal_adagrad}_op.*
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like


@register('sign')
def _sign(ins, attrs, ctx):
    x = ins['X'][0]
    return {'Out': like(x, jnp.sign(data_of(x)))}


@register('cumsum')
def _cumsum(ins, attrs, ctx):
    xv = ins['X'][0]
    x = data_of(xv)
    axis = int(attrs.get('axis', -1))
    exclusive = bool(attrs.get('exclusive', False))
    reverse = bool(attrs.get('reverse', False))
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return {'Out': like(xv, out)}


@register('l1_norm')
def _l1_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.sum(jnp.abs(x)).reshape(1)}


@register('squared_l2_norm')
def _squared_l2_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.sum(jnp.square(x)).reshape(1)}


@register('squared_l2_distance')
def _squared_l2_distance(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    sub = x - y            # y broadcasts when it has one row
    n = sub.shape[0]
    return {'Out': jnp.sum(jnp.square(sub).reshape(n, -1), axis=1,
                           keepdims=True),
            'sub_result': sub}


@register('minus')
def _minus(ins, attrs, ctx):
    from ..lowering import first_seq
    x, y = ins['X'][0], ins['Y'][0]
    return {'Out': like(first_seq(x, y), data_of(x) - data_of(y))}


@register('fill')
def _fill(ins, attrs, ctx):
    from .tensor_ops import _np_dtype
    shape = [int(s) for s in attrs['shape']]
    vals = jnp.asarray(np.asarray(attrs['value'], dtype='float64'))
    return {'Out': vals.reshape(shape).astype(
        _np_dtype(attrs.get('dtype', 'float32')))}


@register('fill_zeros_like')
def _fill_zeros_like(ins, attrs, ctx):
    x = ins['X'][0]
    return {'Out': like(x, jnp.zeros_like(data_of(x)))}


@register('norm')
def _norm(ins, attrs, ctx):
    """L2-normalize along `axis` (reference norm_op.cc): Out = X / norm,
    norm = sqrt(sum(x^2, axis) + epsilon)."""
    x = data_of(ins['X'][0])
    axis = int(attrs.get('axis', 1))
    eps = float(attrs.get('epsilon', 1e-10))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {'Out': x / norm, 'Norm': norm}


@register('log_loss')
def _log_loss(ins, attrs, ctx):
    p = data_of(ins['Predicted'][0])
    y = data_of(ins['Labels'][0])
    eps = float(attrs.get('epsilon', 1e-4))
    out = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {'Loss': out}


@register('hinge_loss')
def _hinge_loss(ins, attrs, ctx):
    logits = data_of(ins['Logits'][0])
    y = data_of(ins['Labels'][0]).astype(logits.dtype)
    return {'Loss': jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * logits)}


@register('margin_rank_loss')
def _margin_rank_loss(ins, attrs, ctx):
    """out = max(0, -label*(x1-x2) + margin); label in {1,-1} says x1
    should rank higher/lower (reference margin_rank_loss_op.cc)."""
    label = data_of(ins['Label'][0])
    x1 = data_of(ins['X1'][0])
    x2 = data_of(ins['X2'][0])
    margin = float(attrs.get('margin', 0.0))
    act = -label * (x1 - x2) + margin
    out = jnp.maximum(0.0, act)
    return {'Out': out, 'Activated': (act > 0).astype(x1.dtype)}


@register('modified_huber_loss')
def _modified_huber_loss(ins, attrs, ctx):
    """z = y'*x with y' in {-1,1}: max(0,1-z)^2 for z >= -1 else -4z
    (reference modified_huber_loss_op.cc)."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0]).astype(x.dtype)
    z = x * (2.0 * y - 1.0)
    quad = jnp.square(jnp.maximum(0.0, 1.0 - z))
    out = jnp.where(z >= -1.0, quad, -4.0 * z)
    return {'Out': out, 'IntermediateVal': z}


@register('sampling_id')
def _sampling_id(ins, attrs, ctx):
    """Sample a category index per row of a probability matrix
    (reference sampling_id_op.cc)."""
    p = data_of(ins['X'][0]).astype(jnp.float32)
    key = ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(p, 1e-30)),
                                 axis=-1)
    return {'Out': ids.astype(jnp.int64)}


@register('conv_shift')
def _conv_shift(ins, attrs, ctx):
    """Circular cross-correlation (reference conv_shift_op.cc): out[i,j] =
    sum_k x[i, (j+k-M/2) mod N] * y[i, k] with y width M odd."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    n = x.shape[1]
    m = y.shape[1]
    if m % 2 == 0:
        raise ValueError(
            'conv_shift filter width must be odd (reference '
            'conv_shift_op.cc enforcement), got %d' % m)
    half = m // 2
    offs = jnp.arange(n)[:, None] + (jnp.arange(m)[None, :] - half)
    gathered = x[:, offs % n]          # [B, N, M]
    return {'Out': jnp.einsum('bnm,bm->bn', gathered, y)}


@register('bilinear_tensor_product')
def _bilinear_tensor_product(ins, attrs, ctx):
    """out[:, k] = x @ W[k] @ y^T (+ bias) — reference
    bilinear_tensor_product_op.cc."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    w = data_of(ins['Weight'][0])             # [K, dx, dy]
    out = jnp.einsum('bi,kij,bj->bk', x, w, y)
    if ins.get('Bias'):
        out = out + data_of(ins['Bias'][0])
    return {'Out': out}


@register('sequence_concat')
def _sequence_concat(ins, attrs, ctx):
    """Concatenate corresponding sequences along time (reference
    sequence_concat_op.cc): out_i = [a_i; b_i], ragged. Dense encoding:
    static width sum(T_k), per-row shifts via traced gathers."""
    from ..lowering import SeqValue
    seqs = [v for v in ins['X']]
    vals = [v if isinstance(v, SeqValue) else None for v in seqs]
    if any(v is None for v in vals):
        raise TypeError('sequence_concat expects lod inputs')
    B = vals[0].data.shape[0]
    total_T = sum(v.data.shape[1] for v in vals)
    cols = jnp.arange(total_T)[None, :]                    # [1, Tt]
    out = jnp.zeros((B, total_T) + vals[0].data.shape[2:],
                    vals[0].data.dtype)
    start = jnp.zeros((B, 1), jnp.int32)
    for v in vals:
        lens = v.lengths.reshape(B, 1).astype(jnp.int32)
        T = v.data.shape[1]
        local = cols - start                               # [B, Tt]
        inside = (local >= 0) & (local < lens)
        idx = jnp.clip(local, 0, T - 1)
        gathered = jnp.take_along_axis(
            v.data, idx.reshape(B, total_T, *([1] * (v.data.ndim - 2))),
            axis=1)
        m = inside.reshape(B, total_T, *([1] * (v.data.ndim - 2)))
        out = jnp.where(m, gathered, out)
        start = start + lens
    new_lens = sum(v.lengths.astype(jnp.int32) for v in vals)
    return {'Out': SeqValue(out, new_lens)}


@register('sequence_slice')
def _sequence_slice(ins, attrs, ctx):
    """Per-sequence slice by offset/length tensors (reference
    sequence_slice_op.cc); output padded to the input's time capacity."""
    from ..lowering import SeqValue
    x = ins['X'][0]
    if not isinstance(x, SeqValue):
        raise TypeError('sequence_slice expects a lod input')
    off = data_of(ins['Offset'][0]).reshape(-1).astype(jnp.int32)
    length = data_of(ins['Length'][0]).reshape(-1).astype(jnp.int32)
    B, T = x.data.shape[:2]
    cols = jnp.arange(T)[None, :]
    idx = jnp.clip(off[:, None] + cols, 0, T - 1)
    out = jnp.take_along_axis(
        x.data, idx.reshape(B, T, *([1] * (x.data.ndim - 2))), axis=1)
    m = (cols < length[:, None]).reshape(
        B, T, *([1] * (x.data.ndim - 2)))
    return {'Out': SeqValue(jnp.where(m, out, 0), length)}


@register('sequence_erase')
def _sequence_erase(ins, attrs, ctx):
    """Remove all occurrences of the given tokens and compact each
    sequence left (reference sequence_erase_op.cc). Traced-safe
    compaction: stable argsort on the drop mask."""
    from ..lowering import SeqValue
    x = ins['X'][0]
    if not isinstance(x, SeqValue):
        raise TypeError('sequence_erase expects a lod input')
    data = x.data
    flat = data.reshape(data.shape[0], data.shape[1])
    valid = x.mask(jnp.bool_)
    drop = jnp.zeros_like(valid)
    for t in np.asarray(attrs.get('tokens', [])):
        drop = drop | (flat == int(t))
    keep = valid & ~drop
    # stable sort moves kept tokens left, preserving order
    order = jnp.argsort(~keep, axis=1, stable=True)
    compacted = jnp.take_along_axis(flat, order, axis=1)
    new_lens = keep.sum(axis=1).astype(jnp.int32)
    cols = jnp.arange(flat.shape[1])[None, :]
    compacted = jnp.where(cols < new_lens[:, None], compacted, 0)
    return {'Out': SeqValue(compacted.reshape(data.shape), new_lens)}


@register('proximal_gd')
def _proximal_gd(ins, attrs, ctx):
    """prox_{l1,l2} gradient step (reference proximal_gd_op.cc):
    p' = sign(z) * max(|z| - lr*l1, 0) / (1 + lr*l2), z = p - lr*g."""
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    lr = data_of(ins['LearningRate'][0]).reshape(())
    l1 = float(attrs.get('l1', 0.0))
    l2 = float(attrs.get('l2', 0.0))
    z = p - lr * g
    out = jnp.sign(z) * jnp.maximum(jnp.abs(z) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {'ParamOut': out}


@register('proximal_adagrad')
def _proximal_adagrad(ins, attrs, ctx):
    """Adagrad accumulator + proximal step (reference
    proximal_adagrad_op.cc)."""
    p = data_of(ins['Param'][0])
    g = data_of(ins['Grad'][0])
    m = data_of(ins['Moment'][0])
    lr = data_of(ins['LearningRate'][0]).reshape(())
    l1 = float(attrs.get('l1', 0.0))
    l2 = float(attrs.get('l2', 0.0))
    m_out = m + g * g
    # adaptive lr scales only the gradient step; the l1/l2 shrinkage uses
    # the PLAIN lr (reference proximal_adagrad_op.h)
    z = p - lr / jnp.sqrt(m_out) * g
    out = jnp.sign(z) * jnp.maximum(jnp.abs(z) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {'ParamOut': out, 'MomentOut': m_out}


def _window_views(x, kh, kw, sh, sw, ph, pw, pad_value):
    """[N,C,H,W] -> (windows [N,C,OH,OW,KH*KW], flat input index of each
    window element [OH,OW,KH*KW] into the PADDED H*W grid, padded dims)."""
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=pad_value)
    hp, wp = h + 2 * ph, w + 2 * pw
    oh = (hp - kh) // sh + 1
    ow = (wp - kw) // sw + 1
    r = jnp.arange(oh)[:, None] * sh + jnp.arange(kh)[None, :]   # [OH,KH]
    cc = jnp.arange(ow)[:, None] * sw + jnp.arange(kw)[None, :]  # [OW,KW]
    t = xp[:, :, r.reshape(-1), :].reshape(n, c, oh, kh, wp)
    t = t[:, :, :, :, cc.reshape(-1)].reshape(n, c, oh, kh, ow, kw)
    win = t.transpose(0, 1, 2, 4, 3, 5).reshape(n, c, oh, ow, kh * kw)
    fidx = (r[:, None, :, None] * wp +
            cc[None, :, None, :]).reshape(oh, ow, kh * kw)
    return win, fidx, hp, wp


@register('max_pool2d_with_index')
def _max_pool2d_with_index(ins, attrs, ctx):
    """Max pool that also emits the argmax's flat index into the input
    feature map (reference pool_with_index_op.cc); the index feeds
    unpool. Honors global_pooling (full-extent kernel, zero padding)."""
    x = data_of(ins['X'][0])
    if attrs.get('global_pooling', False):
        kh, kw = x.shape[2], x.shape[3]
        sh = sw = 1
        ph = pw = 0
    else:
        kh, kw = [int(k) for k in attrs['ksize']]
        sh, sw = [int(s) for s in attrs.get('strides', [1, 1])]
        ph, pw = [int(p) for p in attrs.get('paddings', [0, 0])]
    neg = jnp.finfo(x.dtype).min
    win, fidx, hp, wp = _window_views(x, kh, kw, sh, sw, ph, pw, neg)
    arg = jnp.argmax(win, axis=-1)                       # [N,C,OH,OW]
    out = jnp.take_along_axis(win, arg[..., None], axis=-1)[..., 0]
    fidx_b = jnp.broadcast_to(fidx, win.shape)
    flat_p = jnp.take_along_axis(fidx_b, arg[..., None],
                                 axis=-1)[..., 0]        # padded-grid idx
    # convert to UNPADDED input coordinates (reference indexes the input)
    rr = flat_p // wp - ph
    cc = flat_p % wp - pw
    mask = (rr >= 0) & (rr < x.shape[2]) & (cc >= 0) & (cc < x.shape[3])
    flat = jnp.where(mask, rr * x.shape[3] + cc, 0)
    return {'Out': out, 'Mask': flat.astype(jnp.int32)}


@register('unpool')
def _unpool(ins, attrs, ctx):
    """Max-unpool by recorded indices (reference unpool_op.cc,
    unpooling_type='max'): each pooled value is written back to its argmax
    position in a zero canvas. Output dims follow the reference InferShape
    ((in-1)*stride - 2*pad + ksize) unless an explicit output_size attr
    overrides. Duplicate indices (overlapping pooling) write the same
    element value, so assignment matches the reference's overwrite."""
    x = data_of(ins['X'][0])                  # [N,C,OH,OW]
    idx = data_of(ins['Indices'][0]).astype(jnp.int32)
    if attrs.get('unpooling_type', 'max') != 'max':
        raise ValueError('only max unpooling exists (reference parity)')
    n, c, ih, iw = x.shape
    if attrs.get('output_size'):
        oh_, ow_ = [int(v) for v in attrs['output_size']]
    else:
        kh, kw = [int(k) for k in attrs['ksize']]
        sh, sw = [int(s) for s in attrs.get('strides', [1, 1])]
        ph, pw = [int(p) for p in attrs.get('paddings', [0, 0])]
        oh_ = (ih - 1) * sh - 2 * ph + kh
        ow_ = (iw - 1) * sw - 2 * pw + kw
    canvas = jnp.zeros((n, c, oh_ * ow_), x.dtype)
    flat_x = x.reshape(n, c, -1)
    flat_i = idx.reshape(n, c, -1)
    ni = jnp.arange(n)[:, None, None]
    ci = jnp.arange(c)[None, :, None]
    canvas = canvas.at[ni, ci, flat_i].set(flat_x)
    return {'Out': canvas.reshape(n, c, oh_, ow_)}


@register('spp')
def _spp(ins, attrs, ctx):
    """Spatial pyramid pooling (reference spp_op.h): level l pools with
    kernel ceil(H/2^l), stride = kernel, padding (kernel*bins - H + 1)//2,
    flattened CHANNEL-major per level and concatenated to
    [N, C*(4^L-1)/3]."""
    x = data_of(ins['X'][0])
    levels = int(attrs['pyramid_height'])
    ptype = attrs.get('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        kh = int(np.ceil(h / bins))
        kw = int(np.ceil(w / bins))
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        if ptype == 'max':
            pad_v = jnp.finfo(x.dtype).min
            win, _, _, _ = _window_views(x, kh, kw, kh, kw, ph, pw, pad_v)
            red = jnp.max(win, axis=-1)            # [N,C,bins,bins]
        else:
            win, _, _, _ = _window_views(x, kh, kw, kh, kw, ph, pw, 0.0)
            # reference 0.14 avg pool divides by the full kernel area
            # (padding included)
            red = jnp.sum(win, axis=-1) / float(kh * kw)
        outs.append(red.reshape(n, c * bins * bins))
    return {'Out': jnp.concatenate(outs, axis=1)}


@register('positive_negative_pair')
def _positive_negative_pair(ins, attrs, ctx):
    """Ranking pair statistics per query (reference
    positive_negative_pair_op.h): over same-query item pairs with
    different labels, a pair is positive when the score order matches the
    label order, negative when inverted, neutral on score ties; weights
    average pairwise. Accumulators chain across batches.

    Pairing is dense [n, n] over the whole batch (masked to same-query
    pairs): XLA fuses the elementwise chain into the three reductions, but
    peak memory is still O(n^2) — for very large ranking evals feed the
    op per query group (the reference's hash-grouping loop is inherently
    host-sequential)."""
    score = data_of(ins['Score'][0]).astype(jnp.float32)
    label = data_of(ins['Label'][0]).astype(jnp.float32).reshape(-1)
    query = data_of(ins['QueryID'][0]).reshape(-1)
    col = int(attrs.get('column', -1))
    s = score[:, col]
    n = s.shape[0]
    if ins.get('Weight'):
        w = data_of(ins['Weight'][0]).astype(jnp.float32).reshape(-1)
    else:
        w = jnp.ones((n,), jnp.float32)
    same_q = query[:, None] == query[None, :]
    diff_l = label[:, None] != label[None, :]
    upper = jnp.triu(jnp.ones((n, n), bool), k=1)
    consider = same_q & diff_l & upper
    pw = (w[:, None] + w[None, :]) * 0.5
    s_d = s[:, None] - s[None, :]
    l_d = label[:, None] - label[None, :]
    tie = s_d == 0
    pos_m = consider & ~tie & (jnp.sign(s_d) == jnp.sign(l_d))
    neg_m = consider & ~tie & (jnp.sign(s_d) != jnp.sign(l_d))
    neu_m = consider & tie
    pos = jnp.sum(jnp.where(pos_m, pw, 0.0)).reshape(1)
    neg = jnp.sum(jnp.where(neg_m, pw, 0.0)).reshape(1)
    neu = jnp.sum(jnp.where(neu_m, pw, 0.0)).reshape(1)
    # accumulators apply only when ALL three are wired (reference &&)
    if (ins.get('AccumulatePositivePair') and ins.get('AccumulateNegativePair')
            and ins.get('AccumulateNeutralPair')):
        pos = pos + data_of(ins['AccumulatePositivePair'][0]).reshape(1)
        neg = neg + data_of(ins['AccumulateNegativePair'][0]).reshape(1)
        neu = neu + data_of(ins['AccumulateNeutralPair'][0]).reshape(1)
    return {'PositivePair': pos, 'NegativePair': neg, 'NeutralPair': neu}


@register('precision_recall')
def _precision_recall(ins, attrs, ctx):
    """Multi-class precision/recall states + macro/micro metrics
    (reference precision_recall_op.h; states columns TP FP TN FN)."""
    idx = data_of(ins['Indices'][0]).reshape(-1).astype(jnp.int32)
    label = data_of(ins['Labels'][0]).reshape(-1).astype(jnp.int32)
    C = int(attrs['class_number'])
    n = idx.shape[0]
    if ins.get('Weights'):
        w = data_of(ins['Weights'][0]).astype(jnp.float32).reshape(-1)
    else:
        w = jnp.ones((n,), jnp.float32)
    oh_pred = jax.nn.one_hot(idx, C, dtype=jnp.float32)
    oh_lbl = jax.nn.one_hot(label, C, dtype=jnp.float32)
    tp = jnp.sum(oh_pred * oh_lbl * w[:, None], axis=0)
    fp = jnp.sum(oh_pred * (1 - oh_lbl) * w[:, None], axis=0)
    fn = jnp.sum(oh_lbl * (1 - oh_pred) * w[:, None], axis=0)
    # TN per class: everything not touching the class (reference
    # increments all-others then corrects)
    total_w = jnp.sum(w)
    tn = total_w - tp - fp - fn
    batch_states = jnp.stack([tp, fp, tn, fn], axis=1)    # [C, 4]
    states = batch_states
    if ins.get('StatesInfo'):
        states = states + data_of(ins['StatesInfo'][0]).astype(jnp.float32)

    def metrics(st):
        # empty classes score 1.0 (reference CalcPrecision/CalcRecall)
        tp_, fp_, fn_ = st[:, 0], st[:, 1], st[:, 3]
        prec = jnp.where(tp_ + fp_ > 0,
                         tp_ / jnp.maximum(tp_ + fp_, 1e-12), 1.0)
        rec = jnp.where(tp_ + fn_ > 0,
                        tp_ / jnp.maximum(tp_ + fn_, 1e-12), 1.0)

        def f1(p, r):
            return jnp.where(p + r > 0,
                             2 * p * r / jnp.maximum(p + r, 1e-12), 0.0)

        macro_p, macro_r = prec.mean(), rec.mean()
        stp, sfp, sfn = tp_.sum(), fp_.sum(), fn_.sum()
        micro_p = jnp.where(stp + sfp > 0,
                            stp / jnp.maximum(stp + sfp, 1e-12), 1.0)
        micro_r = jnp.where(stp + sfn > 0,
                            stp / jnp.maximum(stp + sfn, 1e-12), 1.0)
        # reference: F1 OF the macro-averaged precision/recall, not the
        # mean of per-class F1s
        return jnp.stack([macro_p, macro_r, f1(macro_p, macro_r),
                          micro_p, micro_r, f1(micro_p, micro_r)])

    return {'BatchMetrics': metrics(batch_states),
            'AccumMetrics': metrics(states),
            'AccumStatesInfo': states}


@register('fake_quantize')
def _fake_quantize(ins, attrs, ctx):
    """Quantization-aware-training preview op (reference
    fake_quantize_op.cc, quantize_type='abs_max'): Out = round(x / scale *
    (2^(bits-1)-1)) with scale = max|x|. The static range_abs_max window
    machinery served CUDA graph rewrites; abs_max (the tested mode) is
    the supported type here."""
    qtype = attrs.get('quantize_type', 'abs_max')
    if qtype != 'abs_max':
        raise ValueError(
            "fake_quantize supports quantize_type='abs_max' (got %r); the "
            "reference's window-based range_abs_max drove CUDA graph "
            "rewriting that has no XLA analogue" % qtype)
    x = data_of(ins['X'][0])
    bits = int(attrs.get('bit_length', 8))
    qmax = float((1 << (bits - 1)) - 1)
    scale = jnp.max(jnp.abs(x))
    q = x / jnp.maximum(scale, 1e-30) * qmax
    # reference Eigen round() is half-away-from-zero; jnp.round is
    # half-to-even
    out = jnp.sign(q) * jnp.floor(jnp.abs(q) + 0.5)
    res = {'Out': out, 'OutMovingScale': scale.reshape(1)}
    if ins.get('InScales'):
        res['OutScales'] = data_of(ins['InScales'][0])
    if ins.get('InCurrentIter'):
        res['OutCurrentIter'] = data_of(ins['InCurrentIter'][0])
    return res


@register('fake_dequantize_max_abs')
def _fake_dequantize_max_abs(ins, attrs, ctx):
    """Inverse of fake_quantize abs_max (reference
    fake_dequantize_op.cc): Out = x * scale / (2^(bits-1)-1)."""
    x = data_of(ins['X'][0])
    scale = data_of(ins['Scale'][0]).reshape(())
    bits = int(attrs.get('num_bits', attrs.get('bit_length', 8)))
    qmax = float((1 << (bits - 1)) - 1)
    return {'Out': x.astype(jnp.float32) * scale / qmax}


@register('mine_hard_examples')
def _mine_hard_examples(ins, attrs, ctx):
    """Hard-negative mining (reference
    detection/mine_hard_examples_op.cc, mining_type='max_negative'):
    candidates are unmatched priors with match_dist < neg_dist_threshold;
    per image the top min(num_pos * neg_pos_ratio, num_candidates) by
    classification loss are selected; NegIndices returns them ascending as
    a LoD sequence. The hard_example mode's sample_size re-matching drove
    a second pserver-era pass and is not rebuilt."""
    from ..lowering import SeqValue
    if attrs.get('mining_type', 'max_negative') != 'max_negative':
        raise ValueError(
            "mine_hard_examples supports mining_type='max_negative'")
    cls_loss = data_of(ins['ClsLoss'][0]).astype(jnp.float32)
    match = data_of(ins['MatchIndices'][0]).astype(jnp.int32)
    dist = data_of(ins['MatchDist'][0]).astype(jnp.float32)
    ratio = float(attrs.get('neg_pos_ratio', 3.0))
    thresh = float(attrs.get('neg_dist_threshold', 0.5))
    N, P = cls_loss.shape

    cand = (match == -1) & (dist < thresh)                 # [N, P]
    num_pos = jnp.sum(match != -1, axis=1)
    num_cand = jnp.sum(cand, axis=1)
    n_sel = jnp.minimum((num_pos * ratio).astype(jnp.int32), num_cand)

    masked = jnp.where(cand, cls_loss, -jnp.inf)
    order = jnp.argsort(-masked, axis=1)                   # loss desc
    rank_of = jnp.argsort(order, axis=1)                   # prior -> rank
    selected = cand & (rank_of < n_sel[:, None])           # [N, P]

    # compact selected prior indices ascending per image
    pidx = jnp.broadcast_to(jnp.arange(P)[None, :], (N, P))
    key = jnp.where(selected, pidx, P)                     # pads sort last
    neg_sorted = jnp.sort(key, axis=1)
    lens = jnp.sum(selected, axis=1).astype(jnp.int32)
    cols = jnp.arange(P)[None, :]
    neg = jnp.where(cols < lens[:, None], neg_sorted, 0)
    return {'NegIndices': SeqValue(neg[..., None].astype(jnp.int32), lens),
            'UpdatedMatchIndices': match}
