"""Long-tail op rules: reference operators that exist only at the C++
level (each has a reference unittest test_<op>_op.py but no v0.14 python
layer). Registered here so `layer_function_generator.generate_layer_fn`
— the reference's own mechanism for exposing registered ops — reaches
them, plus the handful of layers/ops.py wrappers.

Parity: paddle/fluid/operators/{sign,cum,l1_norm,squared_l2_norm,
squared_l2_distance,minus,fill,fill_zeros_like,norm,log_loss,hinge_loss,
margin_rank_loss,modified_huber_loss,sampling_id,conv_shift}_op.*
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..lowering import register, data_of, like


@register('sign')
def _sign(ins, attrs, ctx):
    x = ins['X'][0]
    return {'Out': like(x, jnp.sign(data_of(x)))}


@register('cumsum')
def _cumsum(ins, attrs, ctx):
    xv = ins['X'][0]
    x = data_of(xv)
    axis = int(attrs.get('axis', -1))
    exclusive = bool(attrs.get('exclusive', False))
    reverse = bool(attrs.get('reverse', False))
    if reverse:
        x = jnp.flip(x, axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis)
    return {'Out': like(xv, out)}


@register('l1_norm')
def _l1_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.sum(jnp.abs(x)).reshape(1)}


@register('squared_l2_norm')
def _squared_l2_norm(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    return {'Out': jnp.sum(jnp.square(x)).reshape(1)}


@register('squared_l2_distance')
def _squared_l2_distance(ins, attrs, ctx):
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    sub = x - y            # y broadcasts when it has one row
    n = sub.shape[0]
    return {'Out': jnp.sum(jnp.square(sub).reshape(n, -1), axis=1,
                           keepdims=True),
            'sub_result': sub}


@register('minus')
def _minus(ins, attrs, ctx):
    from ..lowering import first_seq
    x, y = ins['X'][0], ins['Y'][0]
    return {'Out': like(first_seq(x, y), data_of(x) - data_of(y))}


@register('fill')
def _fill(ins, attrs, ctx):
    from .tensor_ops import _np_dtype
    shape = [int(s) for s in attrs['shape']]
    vals = jnp.asarray(np.asarray(attrs['value'], dtype='float64'))
    return {'Out': vals.reshape(shape).astype(
        _np_dtype(attrs.get('dtype', 'float32')))}


@register('fill_zeros_like')
def _fill_zeros_like(ins, attrs, ctx):
    x = ins['X'][0]
    return {'Out': like(x, jnp.zeros_like(data_of(x)))}


@register('norm')
def _norm(ins, attrs, ctx):
    """L2-normalize along `axis` (reference norm_op.cc): Out = X / norm,
    norm = sqrt(sum(x^2, axis) + epsilon)."""
    x = data_of(ins['X'][0])
    axis = int(attrs.get('axis', 1))
    eps = float(attrs.get('epsilon', 1e-10))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {'Out': x / norm, 'Norm': norm}


@register('log_loss')
def _log_loss(ins, attrs, ctx):
    p = data_of(ins['Predicted'][0])
    y = data_of(ins['Labels'][0])
    eps = float(attrs.get('epsilon', 1e-4))
    out = -y * jnp.log(p + eps) - (1.0 - y) * jnp.log(1.0 - p + eps)
    return {'Loss': out}


@register('hinge_loss')
def _hinge_loss(ins, attrs, ctx):
    logits = data_of(ins['Logits'][0])
    y = data_of(ins['Labels'][0]).astype(logits.dtype)
    return {'Loss': jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * logits)}


@register('margin_rank_loss')
def _margin_rank_loss(ins, attrs, ctx):
    """out = max(0, -label*(x1-x2) + margin); label in {1,-1} says x1
    should rank higher/lower (reference margin_rank_loss_op.cc)."""
    label = data_of(ins['Label'][0])
    x1 = data_of(ins['X1'][0])
    x2 = data_of(ins['X2'][0])
    margin = float(attrs.get('margin', 0.0))
    act = -label * (x1 - x2) + margin
    out = jnp.maximum(0.0, act)
    return {'Out': out, 'Activated': (act > 0).astype(x1.dtype)}


@register('modified_huber_loss')
def _modified_huber_loss(ins, attrs, ctx):
    """z = y'*x with y' in {-1,1}: max(0,1-z)^2 for z >= -1 else -4z
    (reference modified_huber_loss_op.cc)."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0]).astype(x.dtype)
    z = x * (2.0 * y - 1.0)
    quad = jnp.square(jnp.maximum(0.0, 1.0 - z))
    out = jnp.where(z >= -1.0, quad, -4.0 * z)
    return {'Out': out, 'IntermediateVal': z}


@register('sampling_id')
def _sampling_id(ins, attrs, ctx):
    """Sample a category index per row of a probability matrix
    (reference sampling_id_op.cc)."""
    p = data_of(ins['X'][0]).astype(jnp.float32)
    key = ctx.rng()
    ids = jax.random.categorical(key, jnp.log(jnp.maximum(p, 1e-30)),
                                 axis=-1)
    return {'Out': ids.astype(jnp.int64)}


@register('conv_shift')
def _conv_shift(ins, attrs, ctx):
    """Circular cross-correlation (reference conv_shift_op.cc): out[i,j] =
    sum_k x[i, (j+k-M/2) mod N] * y[i, k] with y width M odd."""
    x = data_of(ins['X'][0])
    y = data_of(ins['Y'][0])
    n = x.shape[1]
    m = y.shape[1]
    if m % 2 == 0:
        raise ValueError(
            'conv_shift filter width must be odd (reference '
            'conv_shift_op.cc enforcement), got %d' % m)
    half = m // 2
    offs = jnp.arange(n)[:, None] + (jnp.arange(m)[None, :] - half)
    gathered = x[:, offs % n]          # [B, N, M]
    return {'Out': jnp.einsum('bnm,bm->bn', gathered, y)}
