"""Program debugging / visualization.

Parity: reference python/paddle/fluid/debugger.py (draw_block_graphviz) +
graphviz.py + the C++ FLAGS_check_nan_inf runtime guard
(paddle/fluid/framework/operator.cc CheckNanInf / operators/isfinite_op).
Emits a text dump, a .dot graph of the op DAG, and a debug-mode executor
switch that runs the step op-by-op checking every float output.
"""
import contextlib

__all__ = ['pprint_program_codes', 'draw_block_graphviz',
           'enable_check_nan_inf', 'disable_check_nan_inf', 'check_nan_inf']

_check_nan_inf = {'active': False}


def enable_check_nan_inf():
    """Run subsequent Executor.run calls op-by-op (un-jitted), raising
    FloatingPointError naming the first op whose output is NaN/Inf."""
    _check_nan_inf['active'] = True


def disable_check_nan_inf():
    _check_nan_inf['active'] = False


def nan_inf_check_active():
    return _check_nan_inf['active']


@contextlib.contextmanager
def check_nan_inf():
    """Scoped debug mode: with debugger.check_nan_inf(): exe.run(...)"""
    prev = _check_nan_inf['active']
    _check_nan_inf['active'] = True
    try:
        yield
    finally:
        _check_nan_inf['active'] = prev


def pprint_program_codes(program):
    print(program.to_string())


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a graphviz dot file of the block's op/var DAG."""
    lines = ["digraph G {", "  rankdir=TB;"]
    highlights = set(highlights or [])
    for i, op in enumerate(block.ops):
        color = 'red' if op.type in highlights else 'lightblue'
        lines.append('  op%d [label="%s" shape=box style=filled fillcolor=%s];'
                     % (i, op.type, color))
        for vs in op.inputs.values():
            for v in vs:
                vid = 'var_%s' % v.name.replace('.', '_').replace('@', '_')
                lines.append('  %s [label="%s" shape=ellipse];' % (vid, v.name))
                lines.append('  %s -> op%d;' % (vid, i))
        for vs in op.outputs.values():
            for v in vs:
                vid = 'var_%s' % v.name.replace('.', '_').replace('@', '_')
                lines.append('  %s [label="%s" shape=ellipse];' % (vid, v.name))
                lines.append('  op%d -> %s;' % (i, vid))
    lines.append("}")
    with open(path, 'w') as f:
        f.write("\n".join(lines))
    return path
