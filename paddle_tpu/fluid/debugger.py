"""Program debugging / visualization.

Parity: reference python/paddle/fluid/debugger.py (draw_block_graphviz) +
graphviz.py. Emits a text dump and a .dot graph of the op DAG.
"""
__all__ = ['pprint_program_codes', 'draw_block_graphviz']


def pprint_program_codes(program):
    print(program.to_string())


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write a graphviz dot file of the block's op/var DAG."""
    lines = ["digraph G {", "  rankdir=TB;"]
    highlights = set(highlights or [])
    for i, op in enumerate(block.ops):
        color = 'red' if op.type in highlights else 'lightblue'
        lines.append('  op%d [label="%s" shape=box style=filled fillcolor=%s];'
                     % (i, op.type, color))
        for vs in op.inputs.values():
            for v in vs:
                vid = 'var_%s' % v.name.replace('.', '_').replace('@', '_')
                lines.append('  %s [label="%s" shape=ellipse];' % (vid, v.name))
                lines.append('  %s -> op%d;' % (vid, i))
        for vs in op.outputs.values():
            for v in vs:
                vid = 'var_%s' % v.name.replace('.', '_').replace('@', '_')
                lines.append('  %s [label="%s" shape=ellipse];' % (vid, v.name))
                lines.append('  op%d -> %s;' % (i, vid))
    lines.append("}")
    with open(path, 'w') as f:
        f.write("\n".join(lines))
    return path
