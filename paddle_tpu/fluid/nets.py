"""Composite networks. Parity: reference python/paddle/fluid/nets.py."""
from . import layers

__all__ = ["simple_img_conv_pool", "sequence_conv_pool", "glu",
           "scaled_dot_product_attention", "img_conv_group"]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, act, param_attr=None,
                         pool_type='max', use_cudnn=True, use_mkldnn=False):
    conv_out = layers.conv2d(input=input, num_filters=num_filters,
                             filter_size=filter_size, param_attr=param_attr,
                             act=act, use_cudnn=use_cudnn)
    pool_out = layers.pool2d(input=conv_out, pool_size=pool_size,
                             pool_type=pool_type, pool_stride=pool_stride,
                             use_cudnn=use_cudnn)
    return pool_out


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type='max', use_cudnn=True,
                   use_mkldnn=False):
    """reference nets.py:img_conv_group (VGG blocks)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def __extend_list__(obj):
        if not hasattr(obj, '__len__'):
            return [obj] * len(conv_num_filter)
        else:
            return list(obj)

    conv_padding = __extend_list__(conv_padding)
    conv_filter_size = __extend_list__(conv_filter_size)
    param_attr = __extend_list__(param_attr)
    conv_with_batchnorm = __extend_list__(conv_with_batchnorm)
    conv_batchnorm_drop_rate = __extend_list__(conv_batchnorm_drop_rate)

    for i in range(len(conv_num_filter)):
        local_conv_act = conv_act
        if conv_with_batchnorm[i]:
            local_conv_act = None
        tmp = layers.conv2d(input=tmp, num_filters=conv_num_filter[i],
                            filter_size=conv_filter_size[i],
                            padding=conv_padding[i],
                            param_attr=param_attr[i], act=local_conv_act,
                            use_cudnn=use_cudnn)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(input=tmp, act=conv_act)
            drop_rate = conv_batchnorm_drop_rate[i]
            if abs(drop_rate) > 1e-5:
                tmp = layers.dropout(x=tmp, dropout_prob=drop_rate)
    pool_out = layers.pool2d(input=tmp, pool_size=pool_size,
                             pool_type=pool_type, pool_stride=pool_stride,
                             use_cudnn=use_cudnn)
    return pool_out


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max"):
    conv_out = layers.sequence_conv(input=input, num_filters=num_filters,
                                    filter_size=filter_size,
                                    param_attr=param_attr, act=act)
    pool_out = layers.sequence_pool(input=conv_out, pool_type=pool_type)
    return pool_out


def glu(input, dim=-1):
    """Gated linear unit (reference nets.py:glu)."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    act_b = layers.sigmoid(x=b)
    out = layers.elementwise_mul(x=a, y=act_b)
    return out


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.):
    """Multi-head scaled-dot-product attention (reference nets.py).
    All matmuls batched for the MXU; on TPU the pallas flash-attention
    kernel (paddle_tpu.ops.flash_attention) is used by the transformer
    model for long sequences."""
    if not (len(queries.shape) == len(keys.shape) == len(values.shape) == 3):
        raise ValueError("inputs must be 3-D")
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("hidden size of queries and keys should be the same")
    if keys.shape[-2] != values.shape[-2]:
        raise ValueError("max seq len of keys and values should be the same")

    def __compute_qkv(queries, keys, values, num_heads):
        if num_heads == 1:
            return queries, keys, values
        q = layers.fc(input=queries, size=queries.shape[-1],
                      num_flatten_dims=2)
        k = layers.fc(input=keys, size=keys.shape[-1], num_flatten_dims=2)
        v = layers.fc(input=values, size=values.shape[-1], num_flatten_dims=2)
        return q, k, v

    def __split_heads(x, num_heads):
        if num_heads == 1:
            return x
        hidden_size = x.shape[-1]
        reshaped = layers.reshape(
            x=x, shape=[0, -1, num_heads, hidden_size // num_heads])
        return layers.transpose(x=reshaped, perm=[0, 2, 1, 3])

    def __combine_heads(x):
        if len(x.shape) == 3:
            return x
        trans_x = layers.transpose(x, perm=[0, 2, 1, 3])
        return layers.reshape(
            x=trans_x,
            shape=[0, -1, int(trans_x.shape[2]) * int(trans_x.shape[3])])

    q, k, v = __compute_qkv(queries, keys, values, num_heads)
    q = __split_heads(q, num_heads)
    k = __split_heads(k, num_heads)
    v = __split_heads(v, num_heads)

    key_dim_per_head = keys.shape[-1] // num_heads

    if not dropout_rate and num_heads > 1:
        # no attention-weight dropout -> ONE fused op (pallas flash
        # attention on TPU, never materializing the [B,H,T,T] weights);
        # with dropout the unfused chain below keeps reference semantics
        ctx = layers.fused_attention(q, k, v,
                                     scale=key_dim_per_head ** -0.5)
        return __combine_heads(ctx)

    scaled_q = layers.scale(x=q, scale=key_dim_per_head ** -0.5)
    product = layers.matmul(x=scaled_q, y=k, transpose_y=True)
    # the reference flattens to 2-D because its softmax op was 2-D-only
    # (nets.py:scaled_dot_product_attention); ours normalizes the last
    # axis at any rank, so softmax applies directly — fewer reshapes for
    # XLA to fuse away
    weights = layers.softmax(product)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate,
                                 is_test=False)
    ctx_multiheads = layers.matmul(weights, v)
    return __combine_heads(ctx_multiheads)
