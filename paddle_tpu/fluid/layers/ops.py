"""Auto-generated-style unary/binary layers. Parity: reference layers/ops.py
(layer_function_generator + __activations__)."""
from ..layer_helper import LayerHelper

__activations__ = [
    'sigmoid', 'logsigmoid', 'exp', 'tanh', 'tanh_shrink', 'softshrink',
    'sqrt', 'abs', 'ceil', 'floor', 'cos', 'sin', 'round', 'reciprocal',
    'square', 'softplus', 'softsign', 'brelu', 'leaky_relu', 'soft_relu',
    'elu', 'relu6', 'pow', 'stanh', 'hard_sigmoid', 'swish',
]

__all__ = __activations__ + [
    'sign', 'cumsum', 'uniform_random', 'hard_shrink', 'thresholded_relu',
    'mean', 'mul', 'scale', 'sigmoid_cross_entropy_with_logits',
    'elementwise_add', 'elementwise_div', 'elementwise_sub',
    'elementwise_mul', 'elementwise_max', 'elementwise_min',
    'elementwise_pow', 'clip', 'clip_by_norm', 'logical_and', 'logical_or',
    'logical_xor', 'logical_not', 'uniform_random_batch_size_like',
    'gaussian_random', 'gaussian_random_batch_size_like', 'sum', 'slice',
    'shape', 'maxout',
]


def _single_in_op(op_type, x, attrs=None, out_dtype=None, x_slot='X',
                  out_slot='Out', name=None, extra_outs=()):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(out_dtype or x.dtype)
    outputs = {out_slot: [out]}
    extras = []
    for slot, dt in extra_outs:
        ev = helper.create_variable_for_type_inference(dt or x.dtype)
        outputs[slot] = [ev]
        extras.append(ev)
    helper.append_op(type=op_type, inputs={x_slot: [x]}, outputs=outputs,
                     attrs=attrs or {})
    return out if not extras else tuple([out] + extras)


def _make_unary(op_type):
    def layer(x, name=None, **kwargs):
        kwargs.pop('act', None)
        return _single_in_op(op_type, x, attrs=kwargs, name=name)
    layer.__name__ = op_type
    layer.__doc__ = ("%s activation (reference layers/ops.py generated "
                     "from operators/activation_op.cc)" % op_type)
    return layer


for _a in __activations__:
    globals()[_a] = _make_unary(_a)


def mean(x, name=None):
    return _single_in_op('mean', x, name=name)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper('mul', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='mul', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [out]},
                     attrs={'x_num_col_dims': x_num_col_dims,
                            'y_num_col_dims': y_num_col_dims})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper('scale', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='scale', inputs={'X': [x]}, outputs={'Out': [out]},
                     attrs={'scale': float(scale), 'bias': float(bias),
                            'bias_after_scale': bias_after_scale})
    if act is None:
        return out
    helper.kwargs['act'] = act
    return helper.append_activation(out)


def sigmoid_cross_entropy_with_logits(x, label, name=None):
    helper = LayerHelper('sigmoid_cross_entropy_with_logits', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='sigmoid_cross_entropy_with_logits',
                     inputs={'X': [x], 'Label': [label]},
                     outputs={'Out': [out]})
    return out


def _make_binary(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(x.dtype)
        helper.append_op(type=op_type, inputs={'X': [x], 'Y': [y]},
                         outputs={'Out': [out]}, attrs={'axis': axis})
        return helper.append_activation(out)
    layer.__name__ = op_type
    return layer


elementwise_add = _make_binary('elementwise_add')
elementwise_sub = _make_binary('elementwise_sub')
elementwise_mul = _make_binary('elementwise_mul')
elementwise_div = _make_binary('elementwise_div')
elementwise_max = _make_binary('elementwise_max')
elementwise_min = _make_binary('elementwise_min')
elementwise_pow = _make_binary('elementwise_pow')


def _make_logical_binary(op_type):
    def layer(x, y, out=None, name=None):
        helper = LayerHelper(op_type, name=name)
        if out is None:
            out = helper.create_variable_for_type_inference('bool')
        helper.append_op(type=op_type, inputs={'X': [x], 'Y': [y]},
                         outputs={'Out': [out]})
        return out
    layer.__name__ = op_type
    return layer


logical_and = _make_logical_binary('logical_and')
logical_or = _make_logical_binary('logical_or')
logical_xor = _make_logical_binary('logical_xor')


def logical_not(x, out=None, name=None):
    helper = LayerHelper('logical_not', name=name)
    if out is None:
        out = helper.create_variable_for_type_inference('bool')
    helper.append_op(type='logical_not', inputs={'X': [x]},
                     outputs={'Out': [out]})
    return out


def clip(x, min, max, name=None):
    return _single_in_op('clip', x, attrs={'min': float(min), 'max': float(max)},
                         name=name)


def clip_by_norm(x, max_norm, name=None):
    return _single_in_op('clip_by_norm', x, attrs={'max_norm': float(max_norm)},
                         name=name)


def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper('uniform_random_batch_size_like', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='uniform_random_batch_size_like',
                     inputs={'Input': [input]}, outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'input_dim_idx': input_dim_idx,
                            'output_dim_idx': output_dim_idx,
                            'min': min, 'max': max, 'seed': seed})
    return out


def gaussian_random(shape, dtype='float32', mean=0.0, std=1.0, seed=0):
    helper = LayerHelper('gaussian_random', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='gaussian_random', outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'mean': mean, 'std': std, 'seed': seed})
    return out


def gaussian_random_batch_size_like(input, shape, dtype='float32',
                                    input_dim_idx=0, output_dim_idx=0,
                                    mean=0.0, std=1.0, seed=0):
    helper = LayerHelper('gaussian_random_batch_size_like', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='gaussian_random_batch_size_like',
                     inputs={'Input': [input]}, outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'input_dim_idx': input_dim_idx,
                            'output_dim_idx': output_dim_idx,
                            'mean': mean, 'std': std, 'seed': seed})
    return out


def sum(x):
    from .tensor import sums
    if not isinstance(x, (list, tuple)):
        x = [x]
    return sums(list(x))


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper('slice', name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(type='slice', inputs={'Input': [input]},
                     outputs={'Out': [out]},
                     attrs={'axes': list(axes), 'starts': list(starts),
                            'ends': list(ends)})
    return out


def shape(input, name=None):
    helper = LayerHelper('shape', name=name)
    out = helper.create_variable_for_type_inference('int32')
    helper.append_op(type='shape', inputs={'Input': [input]},
                     outputs={'Out': [out]})
    return out


def maxout(x, groups, name=None):
    return _single_in_op('maxout', x, attrs={'groups': groups}, name=name)


def uniform_random(shape, dtype='float32', min=-1.0, max=1.0, seed=0):
    """Uniform-random tensor of a static shape (reference layers/ops.py:77,
    operators/uniform_random_op.cc). Lowered to jax.random.uniform keyed on
    the step's threaded PRNG — `seed` is accepted for API parity; the
    executor's key stream already gives run-to-run determinism."""
    helper = LayerHelper('uniform_random', **locals())
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='uniform_random', outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': dtype,
                            'min': min, 'max': max, 'seed': seed})
    return out


def hard_shrink(x, threshold=None):
    """Hard-shrink: x where |x| > threshold else 0 (reference
    layers/ops.py:97, operators/activation_op.cc HardShrink, default 0.5)."""
    attrs = {} if threshold is None else {'threshold': float(threshold)}
    return _single_in_op('hard_shrink', x, attrs=attrs)


def thresholded_relu(x, threshold=None):
    """Thresholded ReLU: x where x > threshold else 0 (reference
    layers/ops.py:140, operators/activation_op.cc ThresholdedRelu,
    default 1.0)."""
    attrs = {} if threshold is None else {'threshold': float(threshold)}
    return _single_in_op('thresholded_relu', x, attrs=attrs)


def sign(x, name=None):
    """Elementwise sign (reference operators/sign_op.cc; no v0.14 python
    layer existed — exposed here alongside the generated activations)."""
    return _single_in_op('sign', x, name=name)


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    """Cumulative sum along axis (reference operators/cum_op.h)."""
    return _single_in_op('cumsum', x,
                         attrs={'axis': axis, 'exclusive': exclusive,
                                'reverse': reverse}, name=name)
