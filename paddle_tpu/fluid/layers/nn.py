"""Neural-network layers. Parity: reference python/paddle/fluid/layers/nn.py
(all 76 public functions + relu/log). Each appends op symbols lowered by
ops_impl/ into the single fused XLA step.
"""
import numpy as np

from ..layer_helper import LayerHelper
from ..framework import Variable
from ..initializer import Normal, Constant
from ..param_attr import ParamAttr
from .. import unique_name
from . import tensor as tensor_mod

__all__ = [
    'fc', 'embedding', 'moe_mlp', 'dynamic_lstm', 'dynamic_lstmp', 'dynamic_gru',
    'gru_unit', 'linear_chain_crf', 'crf_decoding', 'cos_sim',
    'cross_entropy', 'square_error_cost', 'chunk_eval', 'sequence_conv',
    'conv2d', 'conv3d', 'sequence_pool', 'sequence_softmax', 'softmax',
    'pool2d', 'pool3d', 'batch_norm', 'beam_search_decode',
    'conv2d_transpose', 'conv3d_transpose', 'sequence_expand', 'lstm_unit',
    'reduce_sum', 'reduce_mean', 'reduce_max', 'reduce_min', 'reduce_prod',
    'sequence_first_step', 'sequence_last_step', 'dropout', 'split',
    'ctc_greedy_decoder', 'edit_distance', 'l2_normalize', 'matmul', 'topk',
    'warpctc', 'sequence_reshape', 'transpose', 'im2sequence', 'nce',
    'hsigmoid', 'beam_search', 'row_conv', 'multiplex', 'layer_norm',
    'softmax_with_cross_entropy', 'smooth_l1', 'one_hot',
    'autoincreased_step_counter', 'reshape', 'lod_reset', 'lrn', 'pad',
    'label_smooth', 'roi_pool', 'dice_loss', 'image_resize',
    'image_resize_short', 'resize_bilinear', 'gather', 'scatter', 'expand',
    'random_crop', 'mean_iou', 'relu', 'log', 'crop', 'rank_loss', 'prelu',
    'flatten', 'sequence_mask', 'stack', 'fused_attention',
]


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       use_mkldnn=False, act=None, is_test=False, name=None):
    """Fully connected (reference nn.py:fc): one mul per input + sum +
    bias + act. The muls land on the MXU."""
    helper = LayerHelper("fc", **locals())
    dtype = helper.input_dtype()
    mul_results = []
    for input_var, param_attr_ in helper.iter_inputs_and_params():
        input_shape = input_var.shape
        if input_var.lod_level > 0 and num_flatten_dims == 1:
            # sequence input [B, T, d]: apply fc per step
            flat_dims = 2
        else:
            flat_dims = num_flatten_dims
        param_shape = [
            int(np.prod(input_shape[flat_dims:]))
        ] + [size]
        w = helper.create_parameter(attr=param_attr_, shape=param_shape,
                                    dtype=dtype, is_bias=False)
        # static out shape (reference mul_op InferShape with
        # y_num_col_dims=1): X.dims[:k] + [size] — bias append and any
        # downstream fc read it (input_shape is non-None here: param_shape
        # above already dereferenced it)
        out_shape = list(input_shape[:flat_dims]) + [size]
        tmp = helper.create_variable_for_type_inference(
            dtype, shape=out_shape,
            lod_level=getattr(input_var, 'lod_level', 0) or 0)
        helper.append_op(
            type="mul", inputs={"X": [input_var], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": flat_dims, "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(
            dtype, shape=mul_results[0].shape,
            lod_level=mul_results[0].lod_level)
        helper.append_op(type="sum", inputs={"X": mul_results},
                         outputs={"Out": [pre_bias]}, attrs={})
    pre_act = helper.append_bias_op(pre_bias, dim_start=-1, dim_end=None)
    return helper.append_activation(pre_act)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype='float32'):
    """reference nn.py:embedding (lookup_table op).

    is_sparse=True routes the table gradient through the touched-rows-only
    SparseRows path (executor sparse plan; reference SelectedRows) when
    the program shape allows it; otherwise the gradient is a dense
    scatter-add fused by XLA.

    is_distributed=True is the pserver row-split rebuilt TPU-native
    (docs/embedding.md): annotate the table row-sharded over a mesh axis
    — ``param_attr=ParamAttr(..., sharding=('model', None))`` — and
    declare the mesh with ``Program.set_mesh``; the lookup then lowers to
    the all_to_all exchange wire (ops_impl/embedding_ops.py) and, with
    is_sparse=True as well (the supported sharded-sparse combination),
    updates stay touched-rows-only per shard. Without the annotation or
    the mesh the flag is INERT — warned about loudly below, since the
    reference accepted it silently while this framework used to too."""
    helper = LayerHelper('embedding', **locals())
    w = helper.create_parameter(attr=helper.param_attr, shape=size,
                                dtype=dtype, is_bias=False)
    dist_axis = None
    if is_distributed:
        spec = getattr(w, 'sharding', None)
        row = spec[0] if spec else None
        if row is not None and isinstance(row, tuple):
            # annotated, but over an axis PRODUCT: GSPMD will still
            # shard the table, only the lookup wire stays dense — a
            # different situation from no annotation at all
            import warnings
            warnings.warn(
                "embedding(is_distributed=True) on table %r row-shards "
                "over the axis product %r — the all_to_all lookup wire "
                "supports a SINGLE row axis, so lookups stay dense "
                "gathers (the table itself still shards). Use one axis, "
                "e.g. sharding=('model', None) (docs/embedding.md)."
                % (w.name, row), UserWarning, stacklevel=2)
            row = None
        elif row is None:
            import warnings
            warnings.warn(
                "embedding(is_distributed=True) on table %r has no row-"
                "sharding annotation — unless one is stamped later (the "
                "DistributeTranspiler shim does, on transpile()), the "
                "flag is INERT and the table will be replicated. Declare "
                "ParamAttr(sharding=('<axis>', None)) on the table and "
                "Program.set_mesh({'<axis>': N, ...}); is_sparse=True + "
                "is_distributed=True is the supported sharded-sparse "
                "combination (docs/embedding.md)." % w.name,
                UserWarning, stacklevel=2)
        else:
            # set_mesh() may legitimately come after the layer calls; a
            # program that still has no mesh (or no such axis) when it
            # COMPILES is warned about there (executor._CompiledStep)
            dist_axis = row
    # static out shape (reference lookup_table_op InferShape): an id
    # column [..., 1] embeds to [..., emb_dim] — downstream layers (fc)
    # read .shape for their own parameter shapes
    in_shape = getattr(input, 'shape', None)
    out_shape = None
    if in_shape is not None and len(in_shape):
        base = list(in_shape[:-1]) if in_shape[-1] == 1 else list(in_shape)
        out_shape = base + [size[-1]]
    tmp = helper.create_variable_for_type_inference(
        dtype, shape=out_shape,
        lod_level=getattr(input, 'lod_level', 0) or 0)
    padding_idx = -1 if padding_idx is None else \
        padding_idx if padding_idx >= 0 else (size[0] + padding_idx)
    attrs = {'is_sparse': is_sparse,
             'is_distributed': is_distributed,
             'padding_idx': padding_idx}
    if dist_axis is not None:
        # static routing for the lowering rule: the table's row axis,
        # resolved here where the annotation is in hand (the rule sees
        # values, not Variables)
        attrs['dist_axis'] = dist_axis
    helper.append_op(type='lookup_table',
                     inputs={'Ids': [input], 'W': [w]},
                     outputs={'Out': [tmp]},
                     attrs=attrs)
    return tmp


def _create_rnn_bias_param(helper, attr, shape, dtype):
    return helper.create_parameter(attr=attr, shape=shape, dtype=dtype,
                                   is_bias=True)


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation='sigmoid', cell_activation='tanh',
                 candidate_activation='tanh', dtype='float32', name=None):
    """reference nn.py:dynamic_lstm — input is the pre-projected gates
    [*, 4*hidden]; lowers to one lax.scan."""
    helper = LayerHelper('lstm', **locals())
    size = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 4 * size], dtype=dtype)
    bias_size = [1, 7 * size] if use_peepholes else [1, 4 * size]
    bias = _create_rnn_bias_param(helper, helper.bias_attr, bias_size, dtype)
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {'Input': [input], 'Weight': [weight], 'Bias': [bias]}
    if h_0 is not None:
        inputs['H0'] = [h_0]
    if c_0 is not None:
        inputs['C0'] = [c_0]
    helper.append_op(type='lstm', inputs=inputs,
                     outputs={'Hidden': [hidden], 'Cell': [cell]},
                     attrs={'use_peepholes': use_peepholes,
                            'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'cell_activation': cell_activation,
                            'candidate_activation': candidate_activation})
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation='sigmoid', cell_activation='tanh',
                  candidate_activation='tanh', proj_activation='tanh',
                  dtype='float32', name=None):
    helper = LayerHelper('lstmp', **locals())
    size = size // 4
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[proj_size, 4 * size], dtype=dtype)
    proj_weight = helper.create_parameter(
        attr=ParamAttr(name=None), shape=[size, proj_size], dtype=dtype)
    bias_size = [1, 7 * size] if use_peepholes else [1, 4 * size]
    bias = _create_rnn_bias_param(helper, helper.bias_attr, bias_size, dtype)
    projection = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='lstmp',
                     inputs={'Input': [input], 'Weight': [weight],
                             'ProjWeight': [proj_weight], 'Bias': [bias]},
                     outputs={'Projection': [projection], 'Cell': [cell]},
                     attrs={'use_peepholes': use_peepholes,
                            'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'cell_activation': cell_activation,
                            'candidate_activation': candidate_activation,
                            'proj_activation': proj_activation})
    return projection, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation='sigmoid',
                candidate_activation='tanh', h_0=None):
    helper = LayerHelper('gru', **locals())
    dtype = helper.input_dtype()
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    bias = _create_rnn_bias_param(helper, helper.bias_attr, [1, 3 * size], dtype)
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {'Input': [input], 'Weight': [weight], 'Bias': [bias]}
    if h_0 is not None:
        inputs['H0'] = [h_0]
    helper.append_op(type='gru', inputs=inputs, outputs={'Hidden': [hidden]},
                     attrs={'is_reverse': is_reverse,
                            'gate_activation': gate_activation,
                            'activation': candidate_activation})
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation='tanh', gate_activation='sigmoid'):
    helper = LayerHelper('gru_unit', **locals())
    dtype = helper.input_dtype()
    size = size // 3
    weight = helper.create_parameter(attr=helper.param_attr,
                                     shape=[size, 3 * size], dtype=dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    reset_hidden_pre = helper.create_variable_for_type_inference(dtype)
    updated_hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {'Input': [input], 'HiddenPrev': [hidden], 'Weight': [weight]}
    if helper.bias_attr:
        bias = helper.create_parameter(attr=helper.bias_attr,
                                       shape=[1, 3 * size], dtype=dtype,
                                       is_bias=True)
        inputs['Bias'] = [bias]
    helper.append_op(type='gru_unit', inputs=inputs,
                     outputs={'Hidden': [updated_hidden],
                              'ResetHiddenPrev': [reset_hidden_pre],
                              'Gate': [gate]},
                     attrs={'activation': activation,
                            'gate_activation': gate_activation})
    return updated_hidden, reset_hidden_pre, gate


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference nn.py:lstm_unit — fc([x, h]) then fused lstm cell."""
    helper = LayerHelper('lstm_unit', **locals())
    if len(x_t.shape) != 2:
        raise ValueError("x_t must be 2-D")
    size = cell_t_prev.shape[1]
    concat_out = tensor_mod.concat(input=[x_t, hidden_t_prev], axis=1)
    fc_out = fc(input=concat_out, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr)
    dtype = x_t.dtype
    c = helper.create_variable_for_type_inference(dtype)
    h = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='lstm_unit',
                     inputs={"X": [fc_out], "C_prev": [cell_t_prev]},
                     outputs={"C": [c], "H": [h]},
                     attrs={"forget_bias": forget_bias})
    return h, c


def linear_chain_crf(input, label, param_attr=None):
    helper = LayerHelper('linear_chain_crf', **locals())
    size = input.shape[-1]
    transition = helper.create_parameter(attr=helper.param_attr,
                                         shape=[size + 2, size],
                                         dtype=helper.input_dtype())
    alpha = helper.create_variable_for_type_inference(helper.input_dtype())
    emission_exps = helper.create_variable_for_type_inference(helper.input_dtype())
    transition_exps = helper.create_variable_for_type_inference(helper.input_dtype())
    log_likelihood = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type='linear_chain_crf',
                     inputs={"Emission": [input], "Transition": [transition],
                             "Label": [label]},
                     outputs={"Alpha": [alpha], "EmissionExps": [emission_exps],
                              "TransitionExps": [transition_exps],
                              "LogLikelihood": [log_likelihood]})
    return log_likelihood


def crf_decoding(input, param_attr, label=None):
    helper = LayerHelper('crf_decoding', **locals())
    transition = helper.get_parameter(param_attr.name)
    viterbi_path = helper.create_variable_for_type_inference('int64')
    inputs = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        inputs["Label"] = [label]
    helper.append_op(type='crf_decoding', inputs=inputs,
                     outputs={"ViterbiPath": [viterbi_path]})
    return viterbi_path


def cos_sim(X, Y):
    helper = LayerHelper('cos_sim', **locals())
    out = helper.create_variable_for_type_inference(dtype=X.dtype)
    xnorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    ynorm = helper.create_variable_for_type_inference(dtype=X.dtype)
    helper.append_op(type='cos_sim', inputs={'X': [X], 'Y': [Y]},
                     outputs={'Out': [out], 'XNorm': [xnorm],
                              'YNorm': [ynorm]})
    return out


def cross_entropy(input, label, soft_label=False):
    helper = LayerHelper('cross_entropy', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='cross_entropy',
                     inputs={'X': [input], 'Label': [label]},
                     outputs={'Y': [out]}, attrs={'soft_label': soft_label})
    return out


def square_error_cost(input, label):
    """reference nn.py:square_error_cost = (input - label)^2."""
    helper = LayerHelper('square_error_cost', **locals())
    minus_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='elementwise_sub',
                     inputs={'X': [input], 'Y': [label]},
                     outputs={'Out': [minus_out]}, attrs={'axis': -1})
    square_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='square', inputs={'X': [minus_out]},
                     outputs={'Out': [square_out]})
    return square_out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    helper = LayerHelper("chunk_eval", **locals())
    precision = helper.create_variable_for_type_inference(dtype="float32")
    recall = helper.create_variable_for_type_inference(dtype="float32")
    f1_score = helper.create_variable_for_type_inference(dtype="float32")
    num_infer_chunks = helper.create_variable_for_type_inference(dtype="int64")
    num_label_chunks = helper.create_variable_for_type_inference(dtype="int64")
    num_correct_chunks = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(
        type="chunk_eval",
        inputs={"Inference": [input], "Label": [label]},
        outputs={"Precision": [precision], "Recall": [recall],
                 "F1-Score": [f1_score],
                 "NumInferChunks": [num_infer_chunks],
                 "NumLabelChunks": [num_label_chunks],
                 "NumCorrectChunks": [num_correct_chunks]},
        attrs={"num_chunk_types": num_chunk_types,
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": excluded_chunk_types or []})
    return (precision, recall, f1_score, num_infer_chunks, num_label_chunks,
            num_correct_chunks)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper('sequence_conv', **locals())
    dtype = helper.input_dtype()
    filter_shape = [filter_size * input.shape[-1], num_filters]
    filter_param = helper.create_parameter(attr=helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    # out shape: input with the feature axis -> num_filters (reference
    # sequence_conv_op InferShape; input.shape is non-None here —
    # filter_shape above already dereferenced it)
    out_shape = list(input.shape[:-1]) + [num_filters]
    pre_bias = helper.create_variable_for_type_inference(
        dtype, shape=out_shape, lod_level=input.lod_level)
    helper.append_op(type='sequence_conv',
                     inputs={'X': [input], 'Filter': [filter_param]},
                     outputs={'Out': [pre_bias]},
                     attrs={'contextStride': filter_stride,
                            'contextStart': -int(filter_size // 2),
                            'contextLength': filter_size})
    pre_act = helper.append_bias_op(pre_bias, dim_start=-1)
    return helper.append_activation(pre_act)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None, data_format='NCHW'):
    """reference nn.py:conv2d (NCHW); data_format='NHWC' runs
    channels-last — the native XLA:TPU layout — with the SAME OIHW filter
    params, so a model switches layout without touching checkpoints."""
    if data_format not in ('NCHW', 'NHWC'):
        raise ValueError("data_format must be 'NCHW' or 'NHWC', got %r"
                         % (data_format,))
    num_channels = (input.shape[-1] if data_format == 'NHWC'
                    else input.shape[1])
    helper = LayerHelper('conv2d', **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    if num_channels % groups != 0:
        raise ValueError("num_channels must be divisible by groups")
    num_filter_channels = num_channels // groups

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    filter_size = _pair(filter_size)
    stride = _pair(stride)
    padding = _pair(padding)
    dilation = _pair(dilation)
    filter_shape = [num_filters, num_filter_channels] + filter_size

    def _get_default_param_initializer():
        std = (2.0 / (filter_size[0] ** 2 * num_channels)) ** 0.5
        return Normal(0.0, std, 0)

    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=_get_default_param_initializer())
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='conv2d',
        inputs={'Input': [input], 'Filter': [filter_param]},
        outputs={"Output": [pre_bias]},
        attrs={'strides': stride, 'paddings': padding, 'dilations': dilation,
               'groups': groups, 'use_cudnn': use_cudnn,
               'data_format': data_format})
    if data_format == 'NHWC':
        pre_act = helper.append_bias_op(pre_bias, dim_start=-1)
    else:
        pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           use_mkldnn=False, act=None, name=None):
    num_channels = input.shape[1]
    helper = LayerHelper('conv3d', **locals())
    dtype = helper.input_dtype()
    groups = groups or 1
    num_filter_channels = num_channels // groups

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    filter_size = _triple(filter_size)
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    filter_shape = [num_filters, num_filter_channels] + filter_size
    std = (2.0 / (int(np.prod(filter_size)) * num_channels)) ** 0.5
    filter_param = helper.create_parameter(
        attr=helper.param_attr, shape=filter_shape, dtype=dtype,
        default_initializer=Normal(0.0, std, 0))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='conv3d',
        inputs={'Input': [input], 'Filter': [filter_param]},
        outputs={"Output": [pre_bias]},
        attrs={'strides': stride, 'paddings': padding, 'dilations': dilation,
               'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def sequence_pool(input, pool_type):
    helper = LayerHelper('sequence_pool', **locals())
    dtype = helper.input_dtype()
    # pooling consumes the innermost LoD level: one output row per inner
    # sequence, same trailing feature dims (reference sequence_pool_op).
    # In the padded [B, T, ...] SeqValue convention that drops the time
    # dim (rank - 1); the batch dim stays dynamic.
    lod = getattr(input, 'lod_level', 0) or 0
    shape = None
    if input.shape is not None:
        shape = (list(input.shape[:1]) + list(input.shape[2:])
                 if lod > 0 and len(input.shape) >= 3 else
                 list(input.shape))
    pool_out = helper.create_variable_for_type_inference(
        dtype, shape=shape, lod_level=max(lod - 1, 0))
    max_index = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sequence_pool", inputs={"X": [input]},
                     outputs={"Out": [pool_out], "MaxIndex": [max_index]},
                     attrs={"pooltype": pool_type.upper()})
    return pool_out


def sequence_first_step(input):
    return sequence_pool(input=input, pool_type="first")


def sequence_last_step(input):
    return sequence_pool(input=input, pool_type="last")


def sequence_softmax(input, param_attr=None, bias_attr=None, use_cudnn=True):
    helper = LayerHelper('sequence_softmax', **locals())
    dtype = helper.input_dtype()
    softmax_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="sequence_softmax", inputs={"X": [input]},
                     outputs={"Out": [softmax_out]}, attrs={})
    return softmax_out


def softmax(input, param_attr=None, bias_attr=None, use_cudnn=True,
            name=None):
    helper = LayerHelper('softmax', **locals())
    dtype = helper.input_dtype()
    softmax_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [softmax_out]}, attrs={})
    return softmax_out


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None,
           data_format='NCHW'):
    if pool_type not in ["max", "avg"]:
        raise ValueError("pool_type must be 'max' or 'avg'")
    if data_format not in ('NCHW', 'NHWC'):
        raise ValueError("data_format must be 'NCHW' or 'NHWC', got %r"
                         % (data_format,))
    if global_pooling is False and pool_size == -1:
        raise ValueError("pool_size must be set without global pooling")

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    helper = LayerHelper('pool2d', **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='pool2d', inputs={"X": [input]}, outputs={"Out": [pool_out]},
        attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
               "global_pooling": global_pooling,
               "strides": _pair(pool_stride),
               "paddings": _pair(pool_padding), "ceil_mode": ceil_mode,
               "data_format": data_format})
    return pool_out


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, use_mkldnn=False, name=None):
    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper('pool3d', **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type='pool3d', inputs={"X": [input]}, outputs={"Out": [pool_out]},
        attrs={"pooling_type": pool_type, "ksize": _triple(pool_size),
               "global_pooling": global_pooling,
               "strides": _triple(pool_stride),
               "paddings": _triple(pool_padding), "ceil_mode": ceil_mode})
    return pool_out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               in_place=False, use_mkldnn=False, name=None,
               moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=False):
    """reference nn.py:batch_norm."""
    if data_layout not in ('NCHW', 'NHWC'):
        raise ValueError("data_layout must be 'NCHW' or 'NHWC', got %r"
                         % (data_layout,))
    helper = LayerHelper('batch_norm', **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    if data_layout == 'NCHW':
        channel_num = input_shape[1]
    else:
        channel_num = input_shape[-1]
    param_shape = [channel_num]

    scale = helper.create_parameter(attr=helper.param_attr, shape=param_shape,
                                    dtype=dtype,
                                    default_initializer=Constant(1.0))
    bias = helper.create_parameter(attr=helper.bias_attr, shape=param_shape,
                                   dtype=dtype, is_bias=True)
    mean = helper.create_parameter(
        attr=ParamAttr(name=moving_mean_name, initializer=Constant(0.0),
                       trainable=False), shape=param_shape, dtype=dtype)
    variance = helper.create_parameter(
        attr=ParamAttr(name=moving_variance_name, initializer=Constant(1.0),
                       trainable=False), shape=param_shape, dtype=dtype)
    mean.stop_gradient = True
    variance.stop_gradient = True

    saved_mean = helper.create_variable_for_type_inference(dtype,
                                                           stop_gradient=True)
    saved_variance = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    batch_norm_out = input if in_place else \
        helper.create_variable_for_type_inference(dtype)

    helper.append_op(
        type="batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [batch_norm_out], "MeanOut": [mean],
                 "VarianceOut": [variance], "SavedMean": [saved_mean],
                 "SavedVariance": [saved_variance]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout})
    return helper.append_activation(batch_norm_out)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper('layer_norm', **locals())
    dtype = helper.input_dtype()
    input_shape = input.shape
    param_shape = [int(np.prod(input_shape[begin_norm_axis:]))]
    inputs = {'X': [input]}
    if scale:
        scale_p = helper.create_parameter(attr=helper.param_attr,
                                          shape=param_shape, dtype=dtype,
                                          default_initializer=Constant(1.0))
        inputs['Scale'] = [scale_p]
    if shift:
        bias_p = helper.create_parameter(attr=helper.bias_attr,
                                         shape=param_shape, dtype=dtype,
                                         is_bias=True)
        inputs['Bias'] = [bias_p]
    mean_out = helper.create_variable_for_type_inference(dtype,
                                                         stop_gradient=True)
    variance_out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=True)
    layer_norm_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="layer_norm", inputs=inputs,
        outputs={"Y": [layer_norm_out], "Mean": [mean_out],
                 "Variance": [variance_out]},
        attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    return helper.append_activation(layer_norm_out)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv2d_transpose", **locals())
    input_channel = input.shape[1]

    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    padding = _pair(padding)
    stride = _pair(stride)
    dilation = _pair(dilation)
    if filter_size is None:
        if output_size is None:
            raise ValueError("output_size must be set when filter_size is None")
        output_size = _pair(output_size)
        h_in, w_in = input.shape[2], input.shape[3]
        filter_size_h = (output_size[0] - (h_in - 1) * stride[0] +
                         2 * padding[0] - 1) // dilation[0] + 1
        filter_size_w = (output_size[1] - (w_in - 1) * stride[1] +
                         2 * padding[1] - 1) // dilation[1] + 1
        filter_size = [filter_size_h, filter_size_w]
    else:
        filter_size = _pair(filter_size)
    groups = 1 if groups is None else groups
    filter_shape = [input_channel, num_filters // groups] + filter_size
    img_filter = helper.create_parameter(dtype=input.dtype,
                                         shape=filter_shape,
                                         attr=helper.param_attr)
    pre_bias = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='conv2d_transpose',
        inputs={'Input': [input], 'Filter': [img_filter]},
        outputs={'Output': [pre_bias]},
        attrs={'strides': stride, 'paddings': padding, 'dilations': dilation,
               'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    helper = LayerHelper("conv3d_transpose", **locals())
    input_channel = input.shape[1]

    def _triple(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    padding = _triple(padding)
    stride = _triple(stride)
    dilation = _triple(dilation)
    if filter_size is None:
        raise ValueError("filter_size is required for conv3d_transpose")
    filter_size = _triple(filter_size)
    groups = 1 if groups is None else groups
    filter_shape = [input_channel, num_filters // groups] + filter_size
    img_filter = helper.create_parameter(dtype=input.dtype,
                                         shape=filter_shape,
                                         attr=helper.param_attr)
    pre_bias = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type='conv3d_transpose',
        inputs={'Input': [input], 'Filter': [img_filter]},
        outputs={'Output': [pre_bias]},
        attrs={'strides': stride, 'paddings': padding, 'dilations': dilation,
               'groups': groups})
    pre_act = helper.append_bias_op(pre_bias, dim_start=1, dim_end=2)
    return helper.append_activation(pre_act)


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper('sequence_expand', **locals())
    dtype = helper.input_dtype('x')
    # out is a SEQUENCE [rows, T(dynamic), features...]: the lowering
    # broadcasts each x row across y's time axis (dense x gains a time
    # dim; sequence x keeps rank with a new T)
    shape = None
    if x.shape is not None:
        feat = (list(x.shape[2:]) if (x.lod_level or 0) > 0
                and len(x.shape) >= 3 else list(x.shape[1:]))
        shape = [x.shape[0], -1] + feat
    tmp = helper.create_variable_for_type_inference(
        dtype, shape=shape,
        lod_level=max(1, getattr(y, 'lod_level', 0) or 0))
    helper.append_op(type='sequence_expand',
                     inputs={'X': [x], 'Y': [y]}, outputs={'Out': [tmp]},
                     attrs={'ref_level': ref_level})
    return tmp


def _reduce_layer(op_type, input, dim, keep_dim, name):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    if dim is not None and not isinstance(dim, (list, tuple)):
        dim = [dim]
    helper.append_op(
        type=op_type, inputs={'X': [input]}, outputs={'Out': [out]},
        attrs={'dim': dim if dim is not None else [0],
               'keep_dim': keep_dim,
               'reduce_all': True if dim is None else False})
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer('reduce_sum', input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer('reduce_mean', input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer('reduce_max', input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer('reduce_min', input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce_layer('reduce_prod', input, dim, keep_dim, name)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None):
    helper = LayerHelper('dropout', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(type='dropout', inputs={'X': [x]},
                     outputs={'Out': [out], 'Mask': [mask]},
                     attrs={'dropout_prob': dropout_prob, 'is_test': is_test,
                            'fix_seed': seed is not None, 'seed': seed or 0})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper('split', **locals())
    input_shape = input.shape
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = None
    else:
        num = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(dtype=input.dtype)
            for _ in range(num)]
    helper.append_op(
        type='split', inputs={'X': [input]}, outputs={'Out': outs},
        attrs={'num': num_or_sections if isinstance(num_or_sections, int) else 0,
               'sections': sections or [], 'axis': dim})
    return outs


def ctc_greedy_decoder(input, blank, name=None):
    helper = LayerHelper("ctc_greedy_decoder", **locals())
    ctc_out = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(type="ctc_align", inputs={"Input": [input]},
                     outputs={"Output": [ctc_out]},
                     attrs={"merge_repeated": True, "blank": blank})
    return ctc_out


def edit_distance(input, label, normalized=True, ignored_tokens=None):
    helper = LayerHelper("edit_distance", **locals())
    edit_distance_out = helper.create_variable_for_type_inference(dtype="float32")
    sequence_num = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(type="edit_distance",
                     inputs={"Hyps": [input], "Refs": [label]},
                     outputs={"Out": [edit_distance_out],
                              "SequenceNum": [sequence_num]},
                     attrs={"normalized": normalized,
                            "ignored_tokens": ignored_tokens or []})
    return edit_distance_out, sequence_num


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    if len(x.shape) == 1:
        axis = 0
    helper = LayerHelper("l2_normalize", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    norm = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="norm" if False else "l2_normalize",
                     inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": 1 if axis is None else axis,
                            "epsilon": epsilon})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper('matmul', **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type='matmul', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [out]},
                     attrs={'transpose_X': transpose_x,
                            'transpose_Y': transpose_y,
                            'alpha': float(alpha)})
    return out


def fused_attention(q, k, v, key_bias=None, causal=False, scale=None,
                    name=None):
    """Whole-attention fused op: softmax(q k^T * scale + bias) v in ONE op.

    q/k/v: [B, H, T, D]. key_bias: optional [B, Tk] (or [B,1,1,Tk]) additive
    bias for padded keys; causal adds lower-triangular masking. On TPU this
    lowers to the pallas flash-attention kernel (paddle_tpu.ops), which
    never materializes the [B,H,Tq,Tk] score matrix in HBM; elsewhere it
    falls back to the XLA chain. Replaces the reference's matmul->softmax->
    matmul op sequence (nets.py scaled_dot_product_attention).
    """
    helper = LayerHelper('fused_attention', **locals())
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    inputs = {'Q': [q], 'K': [k], 'V': [v]}
    if key_bias is not None:
        inputs['KeyBias'] = [key_bias]
    helper.append_op(type='flash_attention', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'causal': bool(causal),
                            'scale': (float(scale) if scale is not None
                                      else -1.0)})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", **locals())
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [values], "Indices": [indices]},
                     attrs={"k": k})
    values.stop_gradient = True
    indices.stop_gradient = True
    return values, indices


def warpctc(input, label, blank=0, norm_by_times=False):
    helper = LayerHelper('warpctc', **locals())
    loss_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    grad_out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='warpctc',
                     inputs={'Logits': [input], 'Label': [label]},
                     outputs={'WarpCTCGrad': [grad_out], 'Loss': [loss_out]},
                     attrs={'blank': blank, 'norm_by_times': norm_by_times})
    return loss_out


def sequence_reshape(input, new_dim):
    helper = LayerHelper('sequence_reshape', **locals())
    out = helper.create_variable_for_type_inference(helper.input_dtype())
    helper.append_op(type='sequence_reshape', inputs={'X': [input]},
                     outputs={'Out': [out]}, attrs={'new_dim': new_dim})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper('transpose', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='transpose', inputs={'X': [x]},
                     outputs={'Out': [out]}, attrs={'axis': perm})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    padding = _pair(padding)
    if len(padding) == 2:
        padding = [padding[0], padding[1], padding[0], padding[1]]
    helper = LayerHelper('im2sequence', **locals())
    out = helper.create_variable_for_type_inference(dtype=helper.input_dtype())
    helper.append_op(type='im2sequence', inputs={'X': [input]},
                     outputs={'Out': [out]},
                     attrs={'kernels': _pair(filter_size),
                            'strides': _pair(stride), 'paddings': padding})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper('row_conv', **locals())
    dtype = helper.input_dtype()
    filter_shape = [future_context_size + 1, input.shape[-1]]
    filter_param = helper.create_parameter(attr=helper.param_attr,
                                           shape=filter_shape, dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='row_conv',
                     inputs={'X': [input], 'Filter': [filter_param]},
                     outputs={'Out': [out]})
    return helper.append_activation(out)


def multiplex(inputs, index):
    helper = LayerHelper('multiplex', **locals())
    if not isinstance(inputs, list) or len(inputs) < 2:
        raise ValueError("multiplex needs >= 2 inputs")
    out = helper.create_variable_for_type_inference(inputs[0].dtype)
    helper.append_op(type='multiplex',
                     inputs={'X': inputs, 'Ids': [index]},
                     outputs={'Out': [out]})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False):
    helper = LayerHelper('softmax_with_cross_entropy', **locals())
    softmax = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(type='softmax_with_cross_entropy',
                     inputs={'Logits': [logits], 'Label': [label]},
                     outputs={'Softmax': [softmax], 'Loss': [loss]},
                     attrs={'soft_label': soft_label})
    return loss


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper('smooth_l1_loss', **locals())
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    loss = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {'X': [x], 'Y': [y]}
    if inside_weight is not None:
        inputs['InsideWeight'] = [inside_weight]
    if outside_weight is not None:
        inputs['OutsideWeight'] = [outside_weight]
    helper.append_op(type='smooth_l1_loss', inputs=inputs,
                     outputs={'Diff': [diff], 'Out': [loss]},
                     attrs={'sigma': sigma if sigma is not None else 1.0})
    return loss


def one_hot(input, depth):
    helper = LayerHelper("one_hot", **locals())
    one_hot_out = helper.create_variable_for_type_inference(dtype='float32')
    helper.append_op(type="one_hot", inputs={'X': [input]},
                     attrs={'depth': depth},
                     outputs={'Out': [one_hot_out]})
    return one_hot_out


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference nn.py:autoincreased_step_counter."""
    helper = LayerHelper('global_step_counter')
    counter_name = counter_name or '@STEP_COUNTER@'
    blk = helper.main_program.global_block()
    if counter_name in blk.vars:
        counter = blk.vars[counter_name]
    else:
        counter = helper.create_global_variable(
            name=counter_name, dtype='int64', shape=[1], persistable=True)
        helper.set_variable_initializer(
            counter, initializer=Constant(value=float(begin - 1)))
    helper.append_op(type='increment', inputs={'X': [counter]},
                     outputs={'Out': [counter]}, attrs={'step': float(step)},
                     infer_shape=False)
    counter.stop_gradient = True
    return counter


def reshape(x, shape, actual_shape=None, act=None, inplace=True, name=None):
    helper = LayerHelper("reshape", **locals())
    reshaped = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="reshape", inputs={"X": [x]},
                     outputs={"Out": [reshaped]},
                     attrs={"shape": [int(d) for d in shape]})
    return helper.append_activation(reshaped)


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset", **locals())
    # the token buffer REGROUPS under the new lod: out is a sequence
    # [n_seqs(dynamic), T(dynamic), features...] where the features are
    # x's trailing dims (lowering flattens valid tokens and re-pads)
    new_lod = (getattr(y, 'lod_level', 0) or 1) if y is not None else 1
    shape = None
    if x.shape is not None:
        feat = (list(x.shape[2:]) if (x.lod_level or 0) > 0
                and len(x.shape) >= 3 else list(x.shape[1:]))
        shape = [-1, -1] + feat
    out = helper.create_variable_for_type_inference(
        dtype=x.dtype, shape=shape, lod_level=new_lod)
    if y is not None:
        helper.append_op(type="lod_reset", inputs={'X': [x], 'Y': [y]},
                         outputs={'Out': [out]})
    elif target_lod is not None:
        helper.append_op(type="lod_reset", inputs={'X': [x]},
                         attrs={'target_lod': list(target_lod)},
                         outputs={'Out': [out]})
    else:
        raise ValueError("y or target_lod must be set")
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper('lrn', **locals())
    dtype = helper.input_dtype()
    if len(input.shape) != 4:
        raise ValueError("Input of lrn must be 4-D (NCHW)")
    mid_out = helper.create_variable_for_type_inference(dtype,
                                                        stop_gradient=True)
    lrn_out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type='lrn', inputs={'X': [input]},
                     outputs={'Out': [lrn_out], 'MidOut': [mid_out]},
                     attrs={'n': n, 'k': k, 'alpha': alpha, 'beta': beta})
    return lrn_out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper('pad', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='pad', inputs={'X': [x]}, outputs={'Out': [out]},
                     attrs={'paddings': list(paddings),
                            'pad_value': float(pad_value)})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    if epsilon > 1.0 or epsilon < 0.0:
        raise ValueError("epsilon must be in [0, 1]")
    helper = LayerHelper("label_smooth", **locals())
    label.stop_gradient = True
    smooth_label = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [smooth_label]},
                     attrs={"epsilon": float(epsilon)})
    return smooth_label


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0):
    helper = LayerHelper('roi_pool', **locals())
    dtype = helper.input_dtype()
    pool_out = helper.create_variable_for_type_inference(dtype)
    argmaxes = helper.create_variable_for_type_inference(dtype='int32')
    helper.append_op(type="roi_pool",
                     inputs={"X": [input], "ROIs": [rois]},
                     outputs={"Out": [pool_out], "Argmax": [argmaxes]},
                     attrs={"pooled_height": pooled_height,
                            "pooled_width": pooled_width,
                            "spatial_scale": spatial_scale})
    return pool_out


def dice_loss(input, label, epsilon=0.00001):
    helper = LayerHelper('dice_loss', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="dice_loss",
                     inputs={"X": [input], "Label": [label]},
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample='BILINEAR'):
    resample_methods = {'BILINEAR': 'bilinear_interp',
                        'NEAREST': 'nearest_interp'}
    if resample not in resample_methods:
        raise ValueError("resample must be BILINEAR or NEAREST")
    if out_shape is None and scale is None:
        raise ValueError("one of out_shape and scale must be set")
    helper = LayerHelper(resample_methods[resample], **locals())
    dtype = helper.input_dtype()
    inputs = {"X": [input]}
    if out_shape is not None:
        if isinstance(out_shape, Variable):
            inputs['OutSize'] = [out_shape]
            out_h = out_w = 0
        else:
            out_h, out_w = int(out_shape[0]), int(out_shape[1])
    else:
        out_h = int(input.shape[2] * scale)
        out_w = int(input.shape[3] * scale)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type=resample_methods[resample], inputs=inputs,
                     outputs={"Out": [out]},
                     attrs={"out_h": out_h, "out_w": out_w})
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, 'BILINEAR')


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    in_shape = input.shape
    if len(in_shape) != 4:
        raise ValueError("image_resize_short needs a 4-D (NCHW) input")
    hw = in_shape[2:4]
    short_idx = hw.index(min(hw))
    out_shape = list(hw)
    out_shape[short_idx] = out_short_len
    out_shape[1 - short_idx] = int(
        float(out_shape[1 - short_idx]) *
        (float(out_short_len) / float(hw[short_idx])) + 0.5)
    return image_resize(input=input, out_shape=out_shape, resample=resample)


def gather(input, index):
    helper = LayerHelper('gather', **locals())
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def expand(x, expand_times, name=None):
    """Tile each dim of x by expand_times (reference
    operators/expand_op.cc; the Python layer landed just after v0.14)."""
    helper = LayerHelper('expand', **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='expand', inputs={'X': [x]},
                     outputs={'Out': [out]},
                     attrs={'expand_times': list(expand_times)})
    return out


def scatter(input, index, updates, name=None):
    helper = LayerHelper('scatter', **locals())
    dtype = helper.input_dtype()
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="scatter",
                     inputs={"X": [input], "Ids": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop", **locals())
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="random_crop", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape)})
    return out


def mean_iou(input, label, num_classes):
    helper = LayerHelper('mean_iou', **locals())
    dtype = helper.input_dtype()
    out_mean_iou = helper.create_variable_for_type_inference(dtype='float32')
    out_wrong = helper.create_variable_for_type_inference(dtype='int32')
    out_correct = helper.create_variable_for_type_inference(dtype='int32')
    helper.append_op(type="mean_iou",
                     inputs={"Predictions": [input], "Labels": [label]},
                     outputs={"OutMeanIou": [out_mean_iou],
                              "OutWrong": [out_wrong],
                              "OutCorrect": [out_correct]},
                     attrs={"num_classes": num_classes})
    return out_mean_iou, out_wrong, out_correct


def relu(x, name=None):
    helper = LayerHelper('relu', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="relu", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def log(x, name=None):
    helper = LayerHelper('log', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type="log", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper('crop', **locals())
    if offsets is None:
        offsets = [0] * len(x.shape)
    out = helper.create_variable_for_type_inference(x.dtype)
    ipts = {'X': [x]}
    attrs = {'offsets': list(offsets)}
    if isinstance(shape, Variable):
        ipts['Y'] = [shape]
    else:
        attrs['shape'] = list(shape)
    helper.append_op(type='crop', inputs=ipts, outputs={'Out': [out]},
                     attrs=attrs)
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper('rank_loss', **locals())
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(type='rank_loss',
                     inputs={"Label": [label], "Left": [left],
                             "Right": [right]},
                     outputs={'Out': [out]})
    return out


def prelu(x, mode, param_attr=None, name=None):
    helper = LayerHelper('prelu', **locals())
    if mode not in ['all', 'channel', 'element']:
        raise ValueError('mode should be one of all, channel, element')
    alpha_shape = [1]
    if mode == 'channel':
        alpha_shape = [1, x.shape[1], 1, 1]
    elif mode == 'element':
        alpha_shape = list(x.shape)
        alpha_shape[0] = 1
    dtype = 'float32'
    alpha = helper.create_parameter(attr=ParamAttr.to_attr(param_attr),
                                    shape=alpha_shape, dtype='float32',
                                    is_bias=False,
                                    default_initializer=Constant(1.0))
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(type="prelu", inputs={"X": [x], 'Alpha': [alpha]},
                     attrs={"mode": mode}, outputs={"Out": [out]})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper('flatten', **locals())
    if not (isinstance(axis, int)) or axis > len(x.shape) or axis < 0:
        raise ValueError("axis must be in [0, rank(x)]")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(type='flatten', inputs={"X": [x]},
                     outputs={'Out': [out]}, attrs={"axis": axis})
    return out


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    helper = LayerHelper('sequence_mask', **locals())
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type='sequence_mask', inputs={'X': [x]},
                     outputs={'Y': [out]},
                     attrs={'maxlen': maxlen if maxlen is not None else -1,
                            'out_dtype': dtype})
    return out


def stack(x, axis=0):
    helper = LayerHelper('stack', **locals())
    if not isinstance(x, list) and not isinstance(x, tuple):
        x = [x]
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op(type='stack', inputs={'X': x}, outputs={'Y': [out]},
                     attrs={'axis': axis})
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None):
    """Noise-contrastive estimation (reference nn.py:nce)."""
    helper = LayerHelper('nce', **locals())
    dim = input.shape[1]
    num_true_class = label.shape[1] if len(label.shape) > 1 else 1
    w = helper.create_parameter(attr=helper.param_attr,
                                shape=[num_total_classes, dim],
                                dtype=input.dtype)
    b = helper.create_parameter(attr=helper.bias_attr,
                                shape=[num_total_classes, 1],
                                dtype=input.dtype, is_bias=True)
    cost = helper.create_variable_for_type_inference(dtype=input.dtype)
    sample_logits = helper.create_variable_for_type_inference(dtype=input.dtype)
    sample_labels = helper.create_variable_for_type_inference(dtype=label.dtype)
    num_neg_samples = 10 if num_neg_samples is None else int(num_neg_samples)
    inputs = {'Input': [input], 'Label': [label], 'Weight': [w], 'Bias': [b]}
    if sample_weight is not None:
        inputs['SampleWeight'] = [sample_weight]
    helper.append_op(type='nce', inputs=inputs,
                     outputs={'Cost': [cost], 'SampleLogits': [sample_logits],
                              'SampleLabels': [sample_labels]},
                     attrs={'num_total_classes': int(num_total_classes),
                            'num_neg_samples': num_neg_samples,
                            'num_true_classes': num_true_class})
    return cost / (num_neg_samples + 1)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None):
    """Hierarchical sigmoid (reference nn.py:hsigmoid)."""
    helper = LayerHelper('hierarchical_sigmoid', **locals())
    dim = input.shape[1]
    weights = helper.create_parameter(attr=helper.param_attr,
                                      shape=[num_classes - 1, dim],
                                      dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    pre_out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"X": [input], "W": [weights], "Label": [label]}
    if helper.bias_attr:
        bias = helper.create_parameter(attr=helper.bias_attr,
                                       shape=[1, num_classes - 1],
                                       dtype=input.dtype, is_bias=True)
        inputs['Bias'] = [bias]
    helper.append_op(type="hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out], "PreOut": [pre_out]},
                     attrs={"num_classes": num_classes})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id, level=0,
                name=None, return_parent_idx=False):
    """One beam-search step (reference nn.py:2658 +
    operators/beam_search_op.cc): dense [batch*beam] layout on TPU with
    explicit parent pointers instead of LoD lineage."""
    helper = LayerHelper('beam_search', **locals())
    selected_scores = helper.create_variable_for_type_inference('float32')
    selected_ids = helper.create_variable_for_type_inference('int64')
    parent_idx = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='beam_search',
                     inputs={'pre_ids': [pre_ids],
                             'pre_scores': [pre_scores],
                             'ids': [ids], 'scores': [scores]},
                     outputs={'selected_ids': [selected_ids],
                              'selected_scores': [selected_scores],
                              'parent_idx': [parent_idx]},
                     attrs={'level': level, 'beam_size': beam_size,
                            'end_id': end_id})
    if return_parent_idx:
        return selected_ids, selected_scores, parent_idx
    return selected_ids, selected_scores


def beam_search_decode(ids, scores, beam_size=None, end_id=0, parents=None,
                       name=None):
    """reference nn.py:2770. Dense contract: ids/scores are stacked
    [T, batch, beam] tensors (use layers.stack over per-step outputs);
    `parents` carries the beam lineage emitted by beam_search. Tokens past
    each sentence's first end_id come out as end_id (padding). beam_size is
    taken from the tensor shape; the arg is accepted for API parity."""
    helper = LayerHelper('beam_search_decode', **locals())
    sentence_ids = helper.create_variable_for_type_inference('int64')
    sentence_scores = helper.create_variable_for_type_inference('float32')
    inputs = {"Ids": [ids], "Scores": [scores]}
    if parents is not None:
        inputs["Parents"] = [parents]
    helper.append_op(type="beam_search_decode",
                     inputs=inputs,
                     outputs={"SentenceIds": [sentence_ids],
                              "SentenceScores": [sentence_scores]},
                     attrs={'end_id': end_id})
    return sentence_ids, sentence_scores


def moe_mlp(input, num_experts, hidden_size, size=None, act='relu',
            capacity_factor=2.0, gate_param_attr=None, param_attr=None,
            bias_attr=None, name=None, top_k=1, return_aux_loss=False):
    """Top-k gated mixture-of-experts FFN (TPU extension; the reference
    predates MoE — its conditional-computation ancestor is layers.Switch).

    Each of `num_experts` experts is a two-layer MLP
    ``act(x @ w1 + b1) @ w2 + b2`` with hidden width `hidden_size`; tokens
    are routed top-k by a learned linear gate with fixed capacity
    (capacity_factor * top_k * tokens / experts; overflow dropped, all
    first choices claiming slots before any second choice). top_k=1 uses
    Switch-style raw-probability gates; top_k>=2 renormalizes the selected
    gates per token (GShard). Under ParallelExecutor or a
    DistributeTranspiler mesh whose dp size divides num_experts, experts
    are sharded num_experts/dp-per-device and dispatch rides two
    all_to_alls (paddle_tpu.parallel.moe); otherwise experts run locally
    with identical semantics.

    With return_aux_loss=True, also returns the scalar Switch/GShard
    load-balancing auxiliary loss (E * sum_e f_e * P_e, minimized at 1.0
    by a uniform router) to add to the training objective with a small
    weight, e.g. ``cost = cost + 0.01 * aux``.

    input: [N, d] tokens or [B, T, d] sequence activations.
    Returns the same shape with the last dim `size` (default d), or
    (out, aux_loss) when return_aux_loss=True.
    """
    from ..ops_impl.moe_ops import supported_acts
    if (act or None) is not None and act not in supported_acts():
        raise ValueError(
            "moe_mlp act=%r is not supported; pick one of %s"
            % (act, sorted(a for a in supported_acts() if a)))
    if not 1 <= int(top_k) <= int(num_experts):
        raise ValueError('moe_mlp top_k=%r must be in [1, num_experts=%d]'
                         % (top_k, num_experts))
    helper = LayerHelper('moe_mlp', **locals())
    dtype = helper.input_dtype()
    d = int(input.shape[-1])
    out_d = int(size) if size is not None else d
    from ..param_attr import ParamAttr
    gate_w = helper.create_parameter(attr=ParamAttr.to_attr(gate_param_attr),
                                     shape=[d, num_experts], dtype=dtype,
                                     is_bias=False)
    w1 = helper.create_parameter(attr=ParamAttr.to_attr(param_attr),
                                 shape=[num_experts, d, hidden_size],
                                 dtype=dtype, is_bias=False)
    b1 = helper.create_parameter(attr=ParamAttr.to_attr(bias_attr),
                                 shape=[num_experts, hidden_size],
                                 dtype=dtype, is_bias=True)
    w2 = helper.create_parameter(attr=ParamAttr.to_attr(param_attr),
                                 shape=[num_experts, hidden_size, out_d],
                                 dtype=dtype, is_bias=False)
    b2 = helper.create_parameter(attr=ParamAttr.to_attr(bias_attr),
                                 shape=[num_experts, out_d], dtype=dtype,
                                 is_bias=True)
    out = helper.create_variable_for_type_inference(dtype)
    aux = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='moe_mlp',
        inputs={'X': [input], 'GateW': [gate_w], 'W1': [w1], 'B1': [b1],
                'W2': [w2], 'B2': [b2]},
        outputs={'Out': [out], 'AuxLoss': [aux]},
        attrs={'num_experts': int(num_experts),
               'capacity_factor': float(capacity_factor),
               'top_k': int(top_k),
               'act': act or ''})
    if return_aux_loss:
        return out, aux
    return out
