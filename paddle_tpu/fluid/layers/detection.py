"""Detection layers (SSD family). Parity: reference layers/detection.py."""
import numpy as np

from ..layer_helper import LayerHelper
from ..framework import Variable
from . import nn
from . import ops as ops_layers
from . import tensor as tensor_mod

__all__ = [
    'prior_box', 'multi_box_head', 'bipartite_match', 'target_assign',
    'detection_output', 'ssd_loss', 'detection_map', 'rpn_target_assign',
    'anchor_generator', 'box_coder', 'iou_similarity',
]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=[1.],
              variance=[0.1, 0.1, 0.2, 0.2], flip=False, clip=False,
              steps=[0.0, 0.0], offset=0.5, name=None):
    """reference layers/detection.py:prior_box."""
    helper = LayerHelper("prior_box", **locals())
    dtype = helper.input_dtype()
    box = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    if not isinstance(min_sizes, (list, tuple)):
        min_sizes = [min_sizes]
    attrs = {'min_sizes': [float(m) for m in min_sizes],
             'aspect_ratios': [float(a) for a in aspect_ratios],
             'variances': [float(v) for v in variance],
             'flip': flip, 'clip': clip,
             'step_w': float(steps[0]), 'step_h': float(steps[1]),
             'offset': offset}
    if max_sizes is not None and len(max_sizes) > 0 and max_sizes[0] > 0:
        if not isinstance(max_sizes, (list, tuple)):
            max_sizes = [max_sizes]
        attrs['max_sizes'] = [float(m) for m in max_sizes]
    helper.append_op(type="prior_box",
                     inputs={"Input": input, "Image": image},
                     outputs={"Boxes": box, "Variances": var}, attrs=attrs)
    box.stop_gradient = True
    var.stop_gradient = True
    return box, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    helper = LayerHelper("box_coder", **locals())
    output_box = helper.create_variable_for_type_inference(prior_box.dtype)
    helper.append_op(type="box_coder",
                     inputs={"PriorBox": prior_box,
                             "PriorBoxVar": prior_box_var,
                             "TargetBox": target_box},
                     attrs={"code_type": code_type,
                            "box_normalized": box_normalized},
                     outputs={"OutputBox": output_box})
    return output_box


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=[0.1, 0.1, 0.2, 0.2], flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD head over multiple feature maps (reference
    layers/detection.py:multi_box_head)."""
    def _reshape_with_axis_(input, axis=1):
        return nn.flatten(input, axis=axis)

    def _is_list_or_tuple_(data):
        return isinstance(data, (list, tuple))

    if not _is_list_or_tuple_(inputs):
        raise ValueError('inputs should be a list of Variables')
    if min_sizes is None:
        num_layer = len(inputs)
        assert num_layer >= 2
        min_sizes = []
        max_sizes = []
        # with 2 maps there is no interpolation range (the reference
        # derivation divides by num_layer-2); one ratio step covers it
        step = (int(np.floor((max_ratio - min_ratio) / (num_layer - 2)))
                if num_layer > 2 else (max_ratio - min_ratio + 1))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.)
            max_sizes.append(base_size * (ratio + step) / 100.)
        min_sizes = [base_size * .10] + min_sizes
        max_sizes = [base_size * .20] + max_sizes

    locs, confs, boxes_list, vars_list = [], [], [], []
    for i, input in enumerate(inputs):
        min_size = min_sizes[i]
        max_size = max_sizes[i] if max_sizes else []
        if not _is_list_or_tuple_(min_size):
            min_size = [min_size]
        if not _is_list_or_tuple_(max_size):
            max_size = [max_size] if max_size else []
        aspect_ratio = aspect_ratios[i]
        if not _is_list_or_tuple_(aspect_ratio):
            aspect_ratio = [aspect_ratio]
        step = [step_w[i] if step_w else 0.0,
                step_h[i] if step_h else 0.0] if (step_w or step_h) else \
            (steps[i] if steps else [0.0, 0.0])
        box, var = prior_box(input, image, min_size, max_size, aspect_ratio,
                             variance, flip, clip, step, offset)
        boxes_list.append(box)
        vars_list.append(var)
        num_boxes = box.shape[2]
        num_loc_output = num_boxes * 4
        mbox_loc = nn.conv2d(input=input, num_filters=num_loc_output,
                             filter_size=kernel_size, padding=pad,
                             stride=stride)
        mbox_loc = nn.transpose(mbox_loc, perm=[0, 2, 3, 1])
        mbox_loc_flatten = nn.flatten(mbox_loc, axis=1)
        locs.append(mbox_loc_flatten)
        num_conf_output = num_boxes * num_classes
        conf_loc = nn.conv2d(input=input, num_filters=num_conf_output,
                             filter_size=kernel_size, padding=pad,
                             stride=stride)
        conf_loc = nn.transpose(conf_loc, perm=[0, 2, 3, 1])
        conf_loc_flatten = nn.flatten(conf_loc, axis=1)
        confs.append(conf_loc_flatten)

    mbox_locs_concat = tensor_mod.concat(locs, axis=1)
    mbox_locs_concat = nn.reshape(mbox_locs_concat, shape=[0, -1, 4])
    mbox_confs_concat = tensor_mod.concat(confs, axis=1)
    mbox_confs_concat = nn.reshape(mbox_confs_concat,
                                   shape=[0, -1, num_classes])
    boxes_flat = [nn.reshape(b, shape=[-1, 4]) for b in boxes_list]
    vars_flat = [nn.reshape(v, shape=[-1, 4]) for v in vars_list]
    box = tensor_mod.concat(boxes_flat)
    var = tensor_mod.concat(vars_flat)
    box.stop_gradient = True
    var.stop_gradient = True
    return mbox_locs_concat, mbox_confs_concat, box, var


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    helper = LayerHelper('bipartite_match', **locals())
    match_indices = helper.create_variable_for_type_inference('int32')
    match_distance = helper.create_variable_for_type_inference(
        dist_matrix.dtype)
    helper.append_op(
        type='bipartite_match', inputs={'DistMat': dist_matrix},
        attrs={'match_type': match_type or 'bipartite',
               'dist_threshold': dist_threshold or 0.5},
        outputs={'ColToRowMatchIndices': match_indices,
                 'ColToRowMatchDist': match_distance})
    return match_indices, match_distance


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    helper = LayerHelper('target_assign', **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    out_weight = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='target_assign',
        inputs={'X': input, 'MatchIndices': matched_indices},
        attrs={'mismatch_value': mismatch_value},
        outputs={'Out': out, 'OutWeight': out_weight})
    return out, out_weight


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """Decode + class NMS (reference layers/detection.py:detection_output).
    Fixed-size padded output on TPU (keep_top_k rows per image)."""
    helper = LayerHelper("detection_output", **locals())
    decoded_box = box_coder(prior_box=prior_box, prior_box_var=prior_box_var,
                            target_box=loc, code_type='decode_center_size')
    scores = nn.softmax(input=scores)
    nmsed_outs = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type="multiclass_nms",
        inputs={'Scores': scores, 'BBoxes': decoded_box},
        outputs={'Out': nmsed_outs},
        attrs={'background_label': background_label,
               'nms_threshold': nms_threshold, 'nms_top_k': nms_top_k,
               'keep_top_k': keep_top_k, 'score_threshold': score_threshold,
               'nms_eta': nms_eta})
    nmsed_outs.stop_gradient = True
    return nmsed_outs


def iou_similarity(x, y, name=None):
    """reference layers/detection.py:iou_similarity."""
    helper = LayerHelper("iou_similarity", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="iou_similarity", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True,
             sample_size=None):
    """reference layers/detection.py:ssd_loss:562.

    TPU-first: the reference composes 13 ops (iou_similarity,
    bipartite_match, target_assign x3, mine_hard_examples, ...); here ONE
    fused dense op does matching, smooth-L1 localization loss, softmax
    confidence loss and max-negative mining (ops_impl/detection_ops.py).
    Returns the per-image loss [N, 1] (prior-summed, normalized by the
    batch-global positive count) matching the reference's output shape.
    """
    if mining_type != 'max_negative':
        raise ValueError("only mining_type='max_negative' is supported "
                         "(the reference's default)")
    helper = LayerHelper('ssd_loss', **locals())
    loss = helper.create_variable_for_type_inference('float32')
    inputs = {'Loc': [location], 'Conf': [confidence], 'GtBox': [gt_box],
              'GtLabel': [gt_label], 'PriorBox': [prior_box]}
    if prior_box_var is not None:
        inputs['PriorBoxVar'] = [prior_box_var]
    helper.append_op(
        type='ssd_loss', inputs=inputs, outputs={'Loss': [loss]},
        attrs={'background_label': background_label,
               'overlap_threshold': overlap_threshold,
               'neg_pos_ratio': neg_pos_ratio,
               'neg_overlap': neg_overlap,
               'loc_loss_weight': loc_loss_weight,
               'conf_loss_weight': conf_loss_weight,
               'match_type': match_type, 'normalize': normalize},
        infer_shape=False)
    loss.shape = (location.shape[0], 1)
    return loss


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.3, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version='integral'):
    """reference layers/detection.py:detection_map:299 (integral AP).
    Stateless per-batch mAP over the dense NMS output."""
    helper = LayerHelper('detection_map', **locals())
    map_out = helper.create_variable_for_type_inference('float32')
    helper.append_op(
        type='detection_map',
        inputs={'DetectRes': [detect_res], 'Label': [label]},
        outputs={'MAP': [map_out]},
        attrs={'class_num': class_num,
               'background_label': background_label,
               'overlap_threshold': overlap_threshold,
               'evaluate_difficult': evaluate_difficult,
               'ap_type': ap_version},
        infer_shape=False)
    map_out.shape = ()
    map_out.stop_gradient = True
    return map_out


def rpn_target_assign(loc, scores, anchor_box, gt_box,
                      rpn_batch_size_per_im=256, fg_fraction=0.25,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3):
    """reference layers/detection.py:rpn_target_assign:56.

    Dense TPU form: exactly rpn_batch_size_per_im samples per image
    (target label -1 marks unused slots) instead of the reference's
    variable-length gathered index lists.
    """
    helper = LayerHelper('rpn_target_assign', **locals())
    pred_score = helper.create_variable_for_type_inference(scores.dtype)
    pred_loc = helper.create_variable_for_type_inference(loc.dtype)
    tgt_lbl = helper.create_variable_for_type_inference('int32')
    tgt_box = helper.create_variable_for_type_inference(loc.dtype)
    helper.append_op(
        type='rpn_target_assign',
        inputs={'Loc': [loc], 'Score': [scores], 'AnchorBox': [anchor_box],
                'GtBox': [gt_box]},
        outputs={'PredScore': [pred_score], 'PredLoc': [pred_loc],
                 'TargetLabel': [tgt_lbl], 'TargetBox': [tgt_box]},
        attrs={'rpn_batch_size_per_im': rpn_batch_size_per_im,
               'fg_fraction': fg_fraction,
               'rpn_positive_overlap': rpn_positive_overlap,
               'rpn_negative_overlap': rpn_negative_overlap},
        infer_shape=False)
    S = int(rpn_batch_size_per_im)
    pred_score.shape = (loc.shape[0], S, 1)
    pred_loc.shape = (loc.shape[0], S, 4)
    tgt_lbl.shape = (loc.shape[0], S, 1)
    tgt_box.shape = (loc.shape[0], S, 4)
    return pred_score, pred_loc, tgt_lbl, tgt_box


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=[0.1, 0.1, 0.2, 0.2], stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", **locals())
    dtype = helper.input_dtype()
    anchor = helper.create_variable_for_type_inference(dtype)
    var = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        type="anchor_generator", inputs={"Input": input},
        outputs={"Anchors": anchor, "Variances": var},
        attrs={'anchor_sizes': [float(a) for a in (anchor_sizes or [64.])],
               'aspect_ratios': [float(a) for a in (aspect_ratios or [1.])],
               'variances': [float(v) for v in variance],
               'stride': [float(s) for s in (stride or [16., 16.])],
               'offset': offset})
    anchor.stop_gradient = True
    var.stop_gradient = True
    return anchor, var
