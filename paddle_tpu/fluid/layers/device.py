"""Device placement helpers. Parity: reference layers/device.py (get_places).
On TPU, placement is expressed through the mesh (parallel_executor /
paddle_tpu.parallel), so this is a thin shim.
"""
__all__ = []


def get_places(device_count=None, device_type=None):
    import jax
    n = device_count or len(jax.devices())
    return list(range(n))
