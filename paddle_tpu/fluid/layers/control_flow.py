"""Structured control-flow layers.

Parity: reference python/paddle/fluid/layers/control_flow.py (While:584,
Switch:1067, IfElse:1315, StaticRNN:289, DynamicRNN:1511, the
LoDTensorArray ops, increment/compare ops, Print).

TPU-first redesign: the reference runs sub-blocks through C++ interpreter
ops (WhileOp / ConditionalBlockOp / RecurrentOp) with one fresh Scope per
iteration. Here each construct builds a real sub-Block in the Program and
appends ONE block-op in the parent; at trace time the block-op's lowering
rule (ops_impl/block_ops.py) executes the sub-block under the matching XLA
structured-control-flow primitive:

    While      -> lax.while_loop (forward-only) or, with max_iters=N, a
                  bounded lax.scan with predicated carries (differentiable)
    StaticRNN  -> lax.scan over the leading time axis
    DynamicRNN -> lax.scan over padded [batch, T, ...] + length masking
    IfElse     -> both branches traced, outputs merged by predicated select
                  (dense semantics: `ie.input(x)` yields the FULL batch, not
                  the reference's gathered true/false row subsets — row
                  partitioning is a dynamic shape, hostile to the MXU)
    Switch     -> all cases traced, first-true-wins select fold

LoDTensorArray is a fixed-capacity device buffer + live length (see
lowering.ArrayValue), so arrays are legal loop carries.
"""
import contextlib

from .. import unique_name
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from . import tensor as tensor_mod

__all__ = [
    'While', 'Switch', 'increment', 'array_write', 'create_array',
    'less_than', 'equal', 'array_read', 'array_length', 'IfElse',
    'DynamicRNN', 'StaticRNN', 'reorder_lod_tensor_by_rank', 'ParallelDo',
    'Print', 'is_empty',
]

# Default slot count for LoDTensorArray buffers (overridable per array via
# create_array/array_write capacity=, or globally by assigning this; the
# lowering-side fallback for attr-less ops is lowering.DEFAULT_ARRAY_CAPACITY).
from ..lowering import DEFAULT_ARRAY_CAPACITY as ARRAY_CAPACITY


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type='increment', inputs={'X': [x]}, outputs={'Out': [out]},
                     attrs={'step': float(value)}, infer_shape=False)
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='less_than', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [cond]})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='equal', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [cond]})
    return cond


def min_(x, y):
    from .ops import elementwise_min
    return elementwise_min(x, y)


def max_(x, y):
    from .ops import elementwise_max
    return elementwise_max(x, y)


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='is_empty', inputs={'X': [x]}, outputs={'Out': [cond]})
    return cond


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    helper = LayerHelper('print', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='print', inputs={'In': [input]},
                     outputs={'Out': [out]},
                     attrs={'first_n': first_n, 'summarize': summarize,
                            'message': message or '',
                            'print_phase': print_phase})
    return out


# ---------------------------------------------------------------------------
# Sub-block analysis helpers
# ---------------------------------------------------------------------------

def _outer_written(sub):
    """Vars written by sub-block ops that live in an ancestor block — the
    loop carries / merge targets."""
    seen, out = set(), []
    for op in sub.ops:
        for vs in op.outputs.values():
            for v in vs:
                if v.block.idx != sub.idx and v.name not in seen:
                    seen.add(v.name)
                    out.append(v)
    return out


def _outer_read(sub):
    """Ancestor vars read by sub-block ops (for prune()/clone bookkeeping)."""
    seen, out = set(), []
    for op in sub.ops:
        for vs in op.inputs.values():
            for v in vs:
                if v.block.idx != sub.idx and v.name not in seen:
                    seen.add(v.name)
                    out.append(v)
    return out


# ---------------------------------------------------------------------------
# LoDTensorArray
# ---------------------------------------------------------------------------

def create_array(dtype, capacity=None):
    """reference layers/control_flow.py:create_array (LOD_TENSOR_ARRAY var)."""
    helper = LayerHelper('create_array', **locals())
    arr = helper.create_variable(
        name=unique_name.generate('array'), shape=None, dtype=dtype,
        type='LOD_TENSOR_ARRAY')
    arr._initialized = False
    arr._elem_shape = None
    arr._capacity = capacity or ARRAY_CAPACITY
    return arr


def array_write(x, i, array=None, capacity=None):
    """Write x into array slot i (lax.dynamic_update_index_in_dim on the
    fixed-capacity buffer). reference control_flow.py:array_write."""
    helper = LayerHelper('array_write', **locals())
    if array is None:
        array = create_array(x.dtype, capacity=capacity)
    inputs = {'X': [x], 'I': [i]}
    if getattr(array, '_initialized', True):
        inputs['Array'] = [array]
    helper.append_op(
        type='array_write', inputs=inputs, outputs={'Out': [array]},
        attrs={'capacity': int(capacity or getattr(array, '_capacity',
                                                   ARRAY_CAPACITY))},
        infer_shape=False)
    array._initialized = True
    if getattr(array, '_elem_shape', None) is None:
        array._elem_shape = x.shape
    # lod rides along too: a downstream fc must see a sequence var to
    # pick the per-step (feature-only) parameter shape. max over ALL
    # writes — beam-search arrays are often seeded with a lod-0 init and
    # then filled with sequence step outputs.
    array._elem_lod_level = max(getattr(array, '_elem_lod_level', 0),
                                getattr(x, 'lod_level', 0) or 0)
    return array


def array_read(array, i):
    """reference control_flow.py:array_read."""
    helper = LayerHelper('array_read', **locals())
    out = helper.create_variable_for_type_inference(
        dtype=array.dtype,
        lod_level=getattr(array, '_elem_lod_level', 0))
    out.shape = getattr(array, '_elem_shape', None)
    helper.append_op(type='array_read', inputs={'Array': [array], 'I': [i]},
                     outputs={'Out': [out]}, infer_shape=False)
    return out


def array_length(array):
    """reference control_flow.py:array_length."""
    helper = LayerHelper('array_length', **locals())
    out = helper.create_variable_for_type_inference(dtype='int64')
    out.shape = (1,)
    out.stop_gradient = True
    helper.append_op(type='array_length', inputs={'Array': [array]},
                     outputs={'Out': [out]}, infer_shape=False)
    return out


# ---------------------------------------------------------------------------
# While
# ---------------------------------------------------------------------------

class While(object):
    """reference layers/control_flow.py:584 (WhileOp sub-block interpreter).

    Usage (identical to the reference)::

        i = layers.zeros(shape=[1], dtype='int64')
        cond = layers.less_than(x=i, y=limit)
        w = layers.While(cond=cond)
        with w.block():
            ...                    # ops; must update cond
            layers.less_than(x=i, y=limit, cond=cond)

    Loop state = every ancestor var written inside the block (arrays
    included); they must hold values before the loop so carry shapes are
    static. `max_iters=N` (extension) lowers to a bounded, differentiable
    scan instead of lax.while_loop — needed if a While sits on the loss path
    of append_backward, since XLA can't reverse-differentiate an unbounded
    while.
    """

    def __init__(self, cond, is_test=False, name=None, max_iters=None):
        self.helper = LayerHelper("while", name=name)
        if cond.dtype != 'bool':
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.max_iters = max_iters

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        parent = main.current_block()
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
        written = _outer_written(sub)
        if self.cond_var.name not in {v.name for v in written} \
                and not self.max_iters:
            import warnings
            warnings.warn("While block never updates its condition %r — the "
                          "loop will not terminate" % self.cond_var.name)
        reads = [v for v in _outer_read(sub)
                 if v.name != self.cond_var.name]
        attrs = {'sub_block': sub.idx}
        if self.max_iters:
            attrs['max_iters'] = int(self.max_iters)
        parent.append_op(
            type='while',
            inputs={'Condition': [self.cond_var], 'X': reads},
            outputs={'Out': written},
            attrs=attrs, infer_shape=False)


# ---------------------------------------------------------------------------
# Switch
# ---------------------------------------------------------------------------

class Switch(object):
    """reference layers/control_flow.py:1067. if/elif/else over scalar bool
    conditions; every case is traced, values merged first-true-wins. Used by
    the learning-rate schedulers exactly like the reference::

        with layers.Switch() as switch:
            with switch.case(step < warmup):
                layers.assign(small_lr, lr)
            with switch.default():
                layers.assign(big_lr, lr)
    """

    def __init__(self, name=None):
        self.helper = LayerHelper('switch', name=name)
        self._cases = []      # (cond_name, sub_idx, [written names], [vars])
        self._reads = []
        self._conds = []

    def __enter__(self):
        return self

    @contextlib.contextmanager
    def _case(self, condition):
        main = self.helper.main_program
        sub = main.create_block()
        try:
            yield
        finally:
            main.rollback()
        written = _outer_written(sub)
        self._cases.append((condition.name if condition is not None else '',
                            sub.idx, [v.name for v in written], written))
        if condition is not None:
            self._conds.append(condition)
        self._reads.extend(_outer_read(sub))

    def case(self, condition):
        return self._case(condition)

    def default(self):
        return self._case(None)

    def __exit__(self, exc_type, exc_val, exc_tb):
        if exc_type is not None:
            return False
        if not self._cases:
            raise ValueError("Switch with no cases")
        defaults = [k for k, c in enumerate(self._cases) if c[0] == '']
        if len(defaults) > 1 or (defaults and
                                 defaults[0] != len(self._cases) - 1):
            raise ValueError("Switch: default() must be the single last case")
        main = self.helper.main_program
        parent = main.current_block()
        union, seen = [], set()
        for _, _, names, vars_ in self._cases:
            for v in vars_:
                if v.name not in seen:
                    seen.add(v.name)
                    union.append(v)
        reads, rseen = [], set()
        for v in self._reads + self._conds:
            if v.name not in rseen and v.name not in seen:
                rseen.add(v.name)
                reads.append(v)
        parent.append_op(
            type='switch',
            inputs={'Conds': self._conds, 'X': reads},
            outputs={'Out': union},
            attrs={'sub_blocks': [c[1] for c in self._cases],
                   'cond_names': [c[0] for c in self._cases],
                   'case_writes': [c[2] for c in self._cases]},
            infer_shape=False)
        return False


# ---------------------------------------------------------------------------
# IfElse
# ---------------------------------------------------------------------------

class IfElse(object):
    """reference layers/control_flow.py:1315.

    Dense-predication semantics: `ie.input(x)` returns the full-batch x in
    both branches (the reference gathers the true/false row subsets — a
    dynamic shape we deliberately avoid on TPU); both branches execute and
    `ie()` returns jnp.where(cond, true, false) per output pair.
    """

    OUT_IF_ELSE_BLOCKS = 2

    def __init__(self, cond, name=None):
        self.helper = LayerHelper('ifelse', name=name)
        if cond.dtype != 'bool':
            raise TypeError("IfElse condition must be a bool Variable")
        self.cond = cond
        self._outs = {True: [], False: []}
        self._blocks = {}
        self._in_branch = None

    @contextlib.contextmanager
    def _branch(self, is_true):
        main = self.helper.main_program
        sub = main.create_block()
        self._in_branch = is_true
        try:
            yield
        finally:
            main.rollback()
            self._in_branch = None
        self._blocks[is_true] = sub

    def true_block(self):
        return self._branch(True)

    def false_block(self):
        return self._branch(False)

    def input(self, x):
        if self._in_branch is None:
            raise ValueError("IfElse.input() must be called inside "
                             "true_block()/false_block()")
        return x

    def output(self, *outs):
        if self._in_branch is None:
            raise ValueError("IfElse.output() must be called inside "
                             "true_block()/false_block()")
        self._outs[self._in_branch].extend(outs)

    def __call__(self):
        if True not in self._blocks or False not in self._blocks:
            raise ValueError("IfElse needs both true_block and false_block")
        t_outs, f_outs = self._outs[True], self._outs[False]
        if len(t_outs) != len(f_outs):
            raise ValueError("IfElse branches must produce the same number "
                             "of outputs (%d vs %d)" % (len(t_outs), len(f_outs)))
        main = self.helper.main_program
        parent = main.current_block()
        merged = []
        for t in t_outs:
            m = parent.create_var(
                name=unique_name.generate(self.helper.name + '.out'),
                shape=t.shape, dtype=t.dtype, lod_level=t.lod_level)
            merged.append(m)
        reads, seen = [], set()
        for sub in (self._blocks[True], self._blocks[False]):
            for v in _outer_read(sub):
                if v.name not in seen and v.name != self.cond.name:
                    seen.add(v.name)
                    reads.append(v)
        # Outer-scope vars written inside a branch (assign(output=...),
        # array_write, ...) merge under the same predicate as the declared
        # outputs — matching Switch, instead of silently dropping them.
        outer_writes, wseen = [], set()
        for sub in (self._blocks[True], self._blocks[False]):
            for v in _outer_written(sub):
                if v.name not in wseen:
                    wseen.add(v.name)
                    outer_writes.append(v)
        parent.append_op(
            type='ifelse',
            inputs={'Cond': [self.cond], 'X': reads},
            outputs={'Out': merged, 'OuterOut': outer_writes},
            attrs={'sub_blocks': [self._blocks[True].idx,
                                  self._blocks[False].idx],
                   'true_outs': [v.name for v in t_outs],
                   'false_outs': [v.name for v in f_outs]},
            infer_shape=False)
        return merged


# ---------------------------------------------------------------------------
# StaticRNN
# ---------------------------------------------------------------------------

class StaticRNN(object):
    """reference layers/control_flow.py:289 (RecurrentOp).

    Steps over the LEADING axis of dense [T, batch, ...] tensors; lowers to
    one differentiable lax.scan::

        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)          # [T,B,D] -> [B,D]
            h_prev = rnn.memory(init=h0)     # or shape=&batch_ref=
            h = layers.fc(input=[x_t, h_prev], size=H)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        outs = rnn()                          # [T,B,H]
    """

    BEFORE_RNN_BLOCK = 0
    IN_RNN_BLOCK = 1
    AFTER_RNN_BLOCK = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.status = StaticRNN.BEFORE_RNN_BLOCK
        self.seq_len = None
        self._step_ins = []    # (outer var, inner var)
        self._mems = []        # {'pre': inner, 'init': outer, 'upd': inner}
        self._outs = []        # (inner var, outer var)
        self._sub = None
        self._parent_idx = None

    @contextlib.contextmanager
    def step(self):
        main = self.helper.main_program
        self._parent_idx = main.current_block_idx
        self._sub = main.create_block()
        self.status = StaticRNN.IN_RNN_BLOCK
        try:
            yield
        finally:
            main.rollback()
            self.status = StaticRNN.AFTER_RNN_BLOCK
        self._complete()

    def _assert_in_rnn_block_(self, method):
        if self.status != StaticRNN.IN_RNN_BLOCK:
            raise ValueError("You must invoke {0} in rnn.step()".format(method))

    def step_input(self, x):
        self._assert_in_rnn_block_('step_input')
        if self.seq_len is None:
            self.seq_len = x.shape[0]
        inner = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '.step_in'),
            shape=x.shape[1:], dtype=x.dtype)
        self._step_ins.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._assert_in_rnn_block_('memory')
        main = self.helper.main_program
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory needs init or (shape, batch_ref)")
            # batch_ref is usually a per-step inner var; the boot op lives in
            # the parent block, so point it at the outer [T, B, ...] sequence
            # (whose batch axis is ref_batch_dim_idx=1, matching the
            # reference's default).
            for o, i in self._step_ins:
                if batch_ref is i:
                    batch_ref = o
                    break
            shape = list(shape)
            if not shape or shape[0] != -1:
                shape = [-1] + shape
            cur = main.current_block_idx
            main.current_block_idx = self._parent_idx
            try:
                init = tensor_mod.fill_constant_batch_size_like(
                    input=batch_ref, shape=shape,
                    dtype='float32', value=float(init_value),
                    input_dim_idx=ref_batch_dim_idx,
                    output_dim_idx=init_batch_dim_idx)
            finally:
                main.current_block_idx = cur
        pre = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '.mem'),
            shape=init.shape, dtype=init.dtype)
        self._mems.append({'pre_var': pre, 'init_var': init, 'upd_var': None})
        return pre

    def update_memory(self, mem, x):
        self._assert_in_rnn_block_('update_memory')
        for m in self._mems:
            if m['pre_var'] is mem:
                m['upd_var'] = x
                return
        raise ValueError("update_memory: %r is not a memory of this RNN"
                         % mem.name)

    def step_output(self, o):
        self._assert_in_rnn_block_('step_output')
        T = self.seq_len if self.seq_len is not None else -1
        outer = self.helper.main_program.block(self._parent_idx).create_var(
            name=unique_name.generate(self.helper.name + '.out'),
            shape=(T,) + tuple(o.shape), dtype=o.dtype)
        self._outs.append((o, outer))

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        if not self._step_ins:
            raise ValueError("StaticRNN needs at least one step_input")
        for m in self._mems:
            if m['upd_var'] is None:
                raise ValueError("memory %r never update_memory'd"
                                 % m['pre_var'].name)
        main = self.helper.main_program
        parent = main.block(self._parent_idx)
        inner_names = ({v.name for _, v in self._step_ins}
                       | {m['pre_var'].name for m in self._mems})
        reads = [v for v in _outer_read(self._sub)
                 if v.name not in inner_names]
        parent.append_op(
            type='static_rnn',
            inputs={'X': [o for o, _ in self._step_ins],
                    'Init': [m['init_var'] for m in self._mems],
                    'Extra': reads},
            outputs={'Out': [outer for _, outer in self._outs]},
            attrs={'sub_block': self._sub.idx,
                   'step_ins': [(o.name, i.name) for o, i in self._step_ins],
                   'mems': [{'pre': m['pre_var'].name,
                             'init': m['init_var'].name,
                             'upd': m['upd_var'].name} for m in self._mems],
                   'outs': [(i.name, o.name) for i, o in self._outs]},
            infer_shape=False)

    def __call__(self, *args, **kwargs):
        if self.status != StaticRNN.AFTER_RNN_BLOCK:
            raise ValueError("RNN output can only be retrieved after rnn.step()")
        outs = [outer for _, outer in self._outs]
        return outs[0] if len(outs) == 1 else outs


# ---------------------------------------------------------------------------
# DynamicRNN
# ---------------------------------------------------------------------------

class DynamicRNN(object):
    """reference layers/control_flow.py:1511.

    Steps over padded [batch, T, ...] sequences (lod_level=1 vars); memory
    updates are masked past each sequence's length, outputs keep the input's
    lod. The reference instead sorts sequences by length and shrinks the
    batch each step (DynamicRNNOp) — a dynamic shape per step, so TPU-first
    this is a fixed-T masked lax.scan (same numerics for masked positions).
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper('dynamic_rnn', name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._step_ins = []    # (outer, inner)
        self._static_ins = []  # (outer, inner)
        self._mems = []        # {'pre_var','init_var','value','shape','upd_var'}
        self._outs = []        # (inner, outer)
        self._sub = None
        self._parent_idx = None

    @contextlib.contextmanager
    def block(self):
        main = self.helper.main_program
        self._parent_idx = main.current_block_idx
        self._sub = main.create_block()
        self.status = DynamicRNN.IN_RNN
        try:
            yield
        finally:
            main.rollback()
            self.status = DynamicRNN.AFTER_RNN
        self._complete()

    def _assert_in_rnn_block_(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError("{0} can only be invoked inside rnn.block()"
                             .format(method))

    def step_input(self, x):
        self._assert_in_rnn_block_('step_input')
        if not x.lod_level:
            raise ValueError("DynamicRNN.step_input expects a lod_level>0 "
                             "sequence var; use StaticRNN for dense tensors")
        inner = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '.step_in'),
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_ins.append((x, inner))
        return inner

    def static_input(self, x):
        self._assert_in_rnn_block_('static_input')
        inner = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '.static_in'),
            shape=x.shape, dtype=x.dtype, lod_level=x.lod_level)
        self._static_ins.append((x, inner))
        return inner

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype='float32'):
        self._assert_in_rnn_block_('memory')
        if init is not None:
            mshape, mdtype = init.shape, init.dtype
        else:
            if shape is None:
                raise ValueError("memory needs init or shape")
            mshape, mdtype = (-1,) + tuple(shape), dtype
        pre = self._sub.create_var(
            name=unique_name.generate(self.helper.name + '.mem'),
            shape=mshape, dtype=mdtype)
        self._mems.append({'pre_var': pre, 'init_var': init,
                           'value': float(value), 'dtype': mdtype,
                           'shape': list(shape) if shape else None,
                           'upd_var': None})
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn_block_('update_memory')
        for m in self._mems:
            if m['pre_var'] is ex_mem:
                m['upd_var'] = new_mem
                return
        raise ValueError("update_memory: %r is not a memory of this RNN"
                         % ex_mem.name)

    def output(self, *outputs):
        self._assert_in_rnn_block_('output')
        for o in outputs:
            T = self._step_ins[0][0].shape[1] if self._step_ins else -1
            outer = self.helper.main_program.block(self._parent_idx).create_var(
                name=unique_name.generate(self.helper.name + '.out'),
                shape=(o.shape[0], T) + tuple(o.shape[1:]), dtype=o.dtype,
                lod_level=1)
            self._outs.append((o, outer))

    def _complete(self):
        if not self._step_ins:
            raise ValueError("DynamicRNN needs at least one step_input")
        for m in self._mems:
            if m['upd_var'] is None:
                raise ValueError("memory %r never update_memory'd"
                                 % m['pre_var'].name)
        main = self.helper.main_program
        parent = main.block(self._parent_idx)
        inner_names = ({v.name for _, v in self._step_ins}
                       | {v.name for _, v in self._static_ins}
                       | {m['pre_var'].name for m in self._mems})
        reads = [v for v in _outer_read(self._sub)
                 if v.name not in inner_names]
        parent.append_op(
            type='dynamic_rnn',
            inputs={'X': [o for o, _ in self._step_ins],
                    'Static': [o for o, _ in self._static_ins],
                    'Init': [m['init_var'] for m in self._mems
                             if m['init_var'] is not None],
                    'Extra': reads},
            outputs={'Out': [outer for _, outer in self._outs]},
            attrs={'sub_block': self._sub.idx,
                   'step_ins': [(o.name, i.name) for o, i in self._step_ins],
                   'static_ins': [(o.name, i.name)
                                  for o, i in self._static_ins],
                   'mems': [{'pre': m['pre_var'].name,
                             'init': (m['init_var'].name
                                      if m['init_var'] is not None else None),
                             'value': m['value'], 'shape': m['shape'],
                             'dtype': m['dtype'],
                             'upd': m['upd_var'].name} for m in self._mems],
                   'outs': [(i.name, o.name) for i, o in self._outs]},
            infer_shape=False)

    def __call__(self, *args, **kwargs):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("Output of DynamicRNN can only be retrieved "
                             "after rnn.block()")
        outs = [outer for _, outer in self._outs]
        return outs[0] if len(outs) == 1 else outs


def reorder_lod_tensor_by_rank(x, rank_table):
    """Identity on TPU: the padded-dense layout never shrinks the batch, so
    the reference's length-rank reordering (reorder_lod_tensor_by_rank_op.cc)
    has nothing to reorder."""
    return x


def ParallelDo(*args, **kwargs):
    raise NotImplementedError(
        "ParallelDo was deprecated in the reference; use ParallelExecutor "
        "(GSPMD data parallelism) instead")
