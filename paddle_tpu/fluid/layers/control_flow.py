"""Control-flow layers.

Parity: reference layers/control_flow.py (While/Switch/IfElse/StaticRNN/
DynamicRNN/arrays/Print). The reference runs sub-blocks through C++
WhileOp/ConditionalBlockOp interpreters; TPU-first these must become
lax.while_loop / lax.cond / lax.scan. Round 1 ships the leaf primitives
(increment/compare/array ops/Print) plus scalar helpers; the block-structured
While/IfElse/StaticRNN/DynamicRNN lower via sub-block tracing in a later
round (recurrent models use the fused lstm/gru scan ops meanwhile).
"""
from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper
from . import tensor as tensor_mod

__all__ = [
    'While', 'Switch', 'increment', 'array_write', 'create_array',
    'less_than', 'equal', 'array_read', 'array_length', 'IfElse',
    'DynamicRNN', 'StaticRNN', 'reorder_lod_tensor_by_rank', 'ParallelDo',
    'Print', 'is_empty',
]


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment", **locals())
    if in_place:
        out = x
    else:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type='increment', inputs={'X': [x]}, outputs={'Out': [out]},
                     attrs={'step': float(value)}, infer_shape=False)
    return out


def less_than(x, y, force_cpu=None, cond=None):
    helper = LayerHelper("less_than", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='less_than', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [cond]})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='equal', inputs={'X': [x], 'Y': [y]},
                     outputs={'Out': [cond]})
    return cond


def min_(x, y):
    from .ops import elementwise_min
    return elementwise_min(x, y)


def max_(x, y):
    from .ops import elementwise_max
    return elementwise_max(x, y)


def is_empty(x, cond=None):
    helper = LayerHelper("is_empty", **locals())
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype='bool')
        cond.stop_gradient = True
    helper.append_op(type='is_empty', inputs={'X': [x]}, outputs={'Out': [cond]})
    return cond


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase='both'):
    helper = LayerHelper('print', **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type='print', inputs={'In': [input]},
                     outputs={'Out': [out]},
                     attrs={'first_n': first_n, 'summarize': summarize,
                            'message': message or '',
                            'print_phase': print_phase})
    return out


# ---- LoDTensorArray emulation ------------------------------------------
# The reference implements arrays as C++ LoDTensorArray vars manipulated by
# array_write/array_read ops inside While blocks. Python-side list semantics
# are enough for the graph-building uses (beam search decode etc.): the
# array var carries a python list of Variables; reads/writes are resolved at
# build time when the index is a constant, which covers the book usages.

class _ArrayVar(object):
    def __init__(self, dtype):
        self.dtype = dtype
        self.items = []


def create_array(dtype):
    return _ArrayVar(dtype)


def array_write(x, i, array=None):
    if array is None:
        array = create_array(x.dtype)
    array.items.append(x)
    return array


def array_read(array, i):
    # constant-index read (resolved at graph-build time)
    if isinstance(i, int):
        return array.items[i]
    import numpy as np
    try:
        idx = int(np.asarray(i))
    except Exception:
        raise NotImplementedError(
            "array_read with a runtime (Variable) index needs the sub-block "
            "control-flow lowering; only build-time-constant indices are "
            "supported so far")
    return array.items[idx]


def array_length(array):
    return tensor_mod.fill_constant(shape=[1], dtype='int64',
                                    value=len(array.items))


class While(object):
    """Reference layers/control_flow.py:While. Full sub-block lowering to
    lax.while_loop lands with the control-flow milestone; constructing it
    today raises with guidance to use the scan-based recurrent layers."""

    def __init__(self, cond, is_test=False, name=None):
        raise NotImplementedError(
            "While: structured control flow lowers to lax.while_loop in the "
            "control-flow milestone; use dynamic_lstm/dynamic_gru (lax.scan) "
            "for recurrence meanwhile")

    class Block(object):
        pass


class Switch(object):
    def __init__(self, name=None):
        raise NotImplementedError("Switch: see While — pending sub-block lowering")


class IfElse(object):
    def __init__(self, cond, name=None):
        raise NotImplementedError("IfElse: see While — pending sub-block lowering")


class StaticRNN(object):
    def __init__(self, name=None):
        raise NotImplementedError(
            "StaticRNN: pending sub-block lowering; use the fused lstm/gru "
            "scan ops (layers.dynamic_lstm/dynamic_gru)")


class DynamicRNN(object):
    def __init__(self, name=None):
        raise NotImplementedError(
            "DynamicRNN: pending sub-block lowering; use the fused lstm/gru "
            "scan ops (layers.dynamic_lstm/dynamic_gru)")


def reorder_lod_tensor_by_rank(x, rank_table):
    raise NotImplementedError(
        "reorder_lod_tensor_by_rank: dense-padded sequences don't need rank "
        "reordering on TPU (no per-sequence batch shrinking)")


def ParallelDo(*args, **kwargs):
    raise NotImplementedError(
        "ParallelDo was deprecated in the reference; use ParallelExecutor "
        "(GSPMD data parallelism) instead")
