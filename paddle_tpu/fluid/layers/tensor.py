"""Tensor-creation layers. Parity: reference layers/tensor.py."""
import numpy as np

from ..framework import Variable, default_main_program, default_startup_program
from ..layer_helper import LayerHelper
from ..initializer import Constant, Initializer
from ..core import convert_dtype

__all__ = [
    'create_tensor', 'create_parameter', 'create_global_var', 'cast',
    'concat', 'sums', 'assign', 'fill_constant_batch_size_like',
    'fill_constant', 'argmin', 'argmax', 'argsort', 'ones', 'zeros',
    'reverse',
]


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter", **locals())
    if attr is None:
        attr = ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias,
                                   default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(dtype=dtype, shape=shape,
                                        persistable=persistable,
                                        name=name)
    helper.set_variable_initializer(var, initializer=Constant(value=float(value)))
    return var


def cast(x, dtype):
    helper = LayerHelper('cast', **locals())
    out = helper.create_variable_for_type_inference(dtype=convert_dtype(dtype))
    helper.append_op(type='cast', inputs={'X': [x]}, outputs={'Out': [out]},
                     attrs={'in_dtype': x.dtype, 'out_dtype': convert_dtype(dtype)})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper('concat', **locals())
    # static out shape (reference concat_op InferShape): inputs' shape
    # with the concat axis summed — downstream fc reads .shape
    shape = None
    shapes = [getattr(v, 'shape', None) for v in input]
    if all(s is not None for s in shapes):
        shape = list(shapes[0])
        ax = axis if axis >= 0 else len(shape) + axis
        if all(len(s) == len(shape) for s in shapes) \
                and all(s[ax] is not None and s[ax] >= 0 for s in shapes):
            shape[ax] = sum(s[ax] for s in shapes)
        else:
            shape = None
    out = helper.create_variable_for_type_inference(
        dtype=helper.input_dtype(), shape=shape,
        lod_level=max((getattr(v, 'lod_level', 0) or 0) for v in input))
    helper.append_op(type='concat', inputs={'X': input},
                     outputs={'Out': [out]}, attrs={'axis': axis})
    return out


def sums(input, out=None):
    helper = LayerHelper('sum', **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=helper.input_dtype())
    helper.append_op(type='sum', inputs={'X': input}, outputs={'Out': out})
    return out


def assign(input, output=None):
    helper = LayerHelper('assign', **locals())
    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op(type='assign', inputs={'X': [input]},
                         outputs={'Out': [output]})
    elif isinstance(input, np.ndarray):
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=str(input.dtype))
        helper.append_op(type='assign_value', outputs={'Out': [output]},
                         attrs={'shape': list(input.shape),
                                'dtype': str(input.dtype),
                                'values': input.reshape(-1).tolist()})
    else:
        raise ValueError("assign expects Variable or numpy.ndarray")
    return output


def fill_constant(shape, dtype, value, force_cpu=False, out=None):
    helper = LayerHelper("fill_constant", **locals())
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=convert_dtype(dtype))
    helper.append_op(type='fill_constant', outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': convert_dtype(dtype),
                            'value': float(value), 'force_cpu': force_cpu})
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like", **locals())
    out = helper.create_variable_for_type_inference(dtype=convert_dtype(dtype))
    helper.append_op(type='fill_constant_batch_size_like',
                     inputs={'Input': [input]}, outputs={'Out': [out]},
                     attrs={'shape': list(shape), 'dtype': convert_dtype(dtype),
                            'value': float(value),
                            'input_dim_idx': input_dim_idx,
                            'output_dim_idx': output_dim_idx})
    out.stop_gradient = True
    return out


def ones(shape, dtype, force_cpu=False):
    return fill_constant(value=1.0, shape=shape, dtype=dtype)


def zeros(shape, dtype, force_cpu=False):
    return fill_constant(value=0.0, shape=shape, dtype=dtype)


def argmin(x, axis=0):
    helper = LayerHelper("arg_min", **locals())
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='arg_min', inputs={'X': x}, outputs={'Out': [out]},
                     attrs={'axis': axis})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max", **locals())
    out = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='arg_max', inputs={'X': x}, outputs={'Out': [out]},
                     attrs={'axis': axis})
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", **locals())
    out = helper.create_variable_for_type_inference(input.dtype)
    ids = helper.create_variable_for_type_inference('int64')
    helper.append_op(type='argsort', inputs={'X': input},
                     outputs={'Out': out, 'Indices': ids},
                     attrs={'axis': axis})
    return out, ids


def reverse(x, axis):
    helper = LayerHelper("reverse", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type='reverse', inputs={'X': x}, outputs={'Out': [out]},
                     attrs={'axis': axis})
    return out
