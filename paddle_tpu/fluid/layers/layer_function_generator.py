"""Layer-function generation utilities.

Parity: reference python/paddle/fluid/layers/layer_function_generator.py,
which reads the C++ OpProto registry and stamps out a Python layer function
per registered operator (generate_layer_fn), plus the deprecated/autodoc/
templatedoc decorators used across layers/*.py.

TPU-first redesign: there is no OpProto registry — ops are lowering rules
(op_type → JAX rule) in paddle_tpu.fluid.lowering. generate_layer_fn stamps
a LayerHelper-based layer for any registered rule: single/multi tensor
inputs map to the rule's canonical 'X'/'Y' slots, remaining kwargs become
op attrs, and one output variable is inferred from the first input's dtype.
The decorators keep the reference's documented semantics so layer code
ported from the reference imports unchanged.
"""
import functools
import re
import string
import warnings

from ..layer_helper import LayerHelper

__all__ = ['deprecated', 'generate_layer_fn', 'autodoc', 'templatedoc']


def deprecated(func_or_class):
    """Mark a layer as deprecated: emits DeprecationWarning on call
    (reference layer_function_generator.py deprecated)."""

    @functools.wraps(func_or_class)
    def wrapper(*args, **kwargs):
        warnings.warn(
            "API {0} is deprecated since paddle_tpu 1.0".format(
                func_or_class.__name__),
            DeprecationWarning, stacklevel=2)
        return func_or_class(*args, **kwargs)

    return wrapper


def autodoc(comment=""):
    """Attach an auto-generated docstring (reference autodoc). With no op
    proto to render, documents the op type and signature."""

    def decorator(func):
        if not func.__doc__:
            func.__doc__ = comment or (
                "Layer %s: lowered to the registered '%s' JAX rule."
                % (func.__name__, func.__name__))
        return func

    return decorator


_TMPL_PATTERN = re.compile(r"\$\{([^}]+)\}")


def templatedoc(op_type=None):
    """Render ${comment}-style placeholders in a layer docstring
    (reference templatedoc). With no OpProto metadata here, ``${comment}``
    renders as the op name, ``${x_comment}`` as the slot name ("x"), and
    ``${x_type}`` as "Variable" (the reference renders proto var types)."""

    def decorator(func):
        doc = func.__doc__ or ""
        tname = op_type or func.__name__

        def _sub(m):
            key = m.group(1)
            if key == 'comment':
                return "The %s operator." % tname
            if key.endswith('_type'):
                return "Variable"
            if key.endswith('_comment'):
                return key[:-len('_comment')]
            return key

        func.__doc__ = _TMPL_PATTERN.sub(_sub, doc)
        return func

    return decorator


def generate_layer_fn(op_type):
    """Stamp a layer function for a registered lowering rule.

    The generated layer mirrors the reference's generated signature:
    positional/keyword tensor inputs (x, y), optional name, remaining
    kwargs become attributes. Reference: layer_function_generator.py
    generate_layer_fn which introspects the OpProto; here the lowering
    registry is the source of truth.
    """
    from ..lowering import has_rule
    if not has_rule(op_type):
        raise ValueError(
            "No lowering rule registered for op '%s'" % op_type)

    def layer(*args, **kwargs):
        helper = LayerHelper(op_type, name=kwargs.pop('name', None),
                             act=kwargs.pop('act', None))
        inputs = {}
        vars_in = list(args)
        for slot_kw in ('input', 'x'):
            if slot_kw in kwargs:
                vars_in.insert(0, kwargs.pop(slot_kw))
        if 'y' in kwargs:
            vars_in.append(kwargs.pop('y'))
        if not vars_in:
            raise ValueError(
                "generate_layer_fn(%s): at least one tensor input required"
                % op_type)
        slots = ['X', 'Y', 'Z'] + list(string.ascii_uppercase[:23])
        for slot, v in zip(slots, vars_in):
            inputs[slot] = [v]
        dtype = kwargs.pop('dtype', None) or vars_in[0].dtype
        out = helper.create_variable_for_type_inference(dtype)
        helper.append_op(type=op_type, inputs=inputs,
                         outputs={'Out': [out]}, attrs=kwargs)
        return helper.append_activation(out)

    layer.__name__ = op_type
    layer.__doc__ = ("Generated layer for the '%s' op (reference "
                     "layer_function_generator.py)." % op_type)
    return layer
