"""IO layers. Parity: reference layers/io.py."""
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper
from ..core import convert_dtype

__all__ = ['data', 'open_recordio_file', 'open_files', 'read_file',
           'shuffle', 'batch', 'double_buffer', 'random_data_generator',
           'py_reader', 'Preprocessor', 'load']


def data(name, shape, append_batch_size=True, dtype='float32', lod_level=0,
         type=None, stop_gradient=True, sharding=None):
    """reference layers/io.py:data.

    sharding: optional GSPMD annotation for the fed value, e.g.
    ``('dp', None)`` (docs/parallel.md). Without it, feeds of a
    mesh-annotated Program shard their batch dim over the mesh's data
    axis automatically."""
    helper = LayerHelper('data', name=name)
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    if lod_level and lod_level > 0:
        # TPU-native padded layout: sequences are dense [batch, time, ...],
        # so the declared fluid shape gains a dynamic time axis.
        shape = [shape[0], -1] + shape[1:]
    return helper.create_global_variable(
        name=name, shape=shape, dtype=convert_dtype(dtype),
        lod_level=lod_level, stop_gradient=stop_gradient, is_data=True,
        sharding=sharding)


class _PyReader(object):
    """Host-side python reader bound to feed targets (replaces the
    reference's C++ reader op chain: open_files -> double_buffer -> read).
    The heavy lifting (threaded prefetch, device staging) lives in
    paddle_tpu.reader.pipeline."""

    def __init__(self, feed_list=None, capacity=64, shapes=None, dtypes=None,
                 lod_levels=None, name=None):
        self.feed_list = feed_list
        self.capacity = capacity
        self.shapes = shapes
        self.dtypes = dtypes
        self.lod_levels = lod_levels
        self._gen = None
        self._vars = None
        if shapes is not None:
            self._vars = []
            for i, (s, d) in enumerate(zip(shapes, dtypes)):
                lod = (lod_levels or [0] * len(shapes))[i]
                self._vars.append(data(
                    name='%s_slot_%d' % (name or 'py_reader', i),
                    shape=list(s)[1:], dtype=d, lod_level=lod))

    def decorate_paddle_reader(self, reader):
        self._gen = reader

    decorate_tensor_provider = decorate_paddle_reader
    decorate_batch_generator = decorate_paddle_reader
    decorate_sample_list_generator = decorate_paddle_reader

    def start(self):
        self._iter = self._gen()

    def reset(self):
        self._iter = None

    def next(self):
        return next(self._iter)

    def __call__(self):
        return self._gen()


def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """reference layers/io.py:py_reader."""
    return _PyReader(capacity=capacity, shapes=shapes, dtypes=dtypes,
                     lod_levels=lod_levels, name=name)


def read_file(reader):
    if isinstance(reader, _PyReader) and reader._vars is not None:
        return reader._vars
    raise TypeError("read_file expects a py_reader with declared shapes")


def open_recordio_file(filename, shapes, lod_levels, dtypes,
                       pass_num=1, for_parallel=True):
    """Chunked record file reader (reference layers/io.py:open_recordio_file);
    backed by paddle_tpu.reader.recordio."""
    from ...reader import recordio as rio

    def gen():
        for _ in range(pass_num):
            for sample in rio.read_samples(filename, shapes, dtypes):
                yield sample

    r = _PyReader(shapes=shapes, dtypes=dtypes, lod_levels=lod_levels)
    r.decorate_paddle_reader(gen)
    return r


def open_files(filenames, shapes, lod_levels, dtypes, thread_num=1,
               buffer_size=None, pass_num=1, for_parallel=True):
    from ...reader import recordio as rio

    def gen():
        for _ in range(pass_num):
            for fn in filenames:
                for sample in rio.read_samples(fn, shapes, dtypes):
                    yield sample

    r = _PyReader(shapes=shapes, dtypes=dtypes, lod_levels=lod_levels)
    r.decorate_paddle_reader(gen)
    return r


def shuffle(reader, buffer_size):
    from ... import reader as reader_mod
    if isinstance(reader, _PyReader):
        inner = reader._gen
        reader._gen = reader_mod.shuffle(inner, buffer_size)
        return reader
    return reader_mod.shuffle(reader, buffer_size)


def batch(reader, batch_size):
    from ...batch import batch as _batch
    if isinstance(reader, _PyReader):
        inner = reader._gen
        reader._gen = _batch(inner, batch_size)
        return reader
    return _batch(reader, batch_size)


def double_buffer(reader, place=None, name=None):
    """Host->device double buffering; wraps the reader with a background
    prefetch thread (reference layers/io.py:double_buffer)."""
    from ...reader.pipeline import prefetch
    if isinstance(reader, _PyReader):
        inner = reader._gen
        reader._gen = prefetch(inner, depth=2)
        return reader
    return prefetch(reader, depth=2)


def random_data_generator(low, high, shapes, lod_levels, for_parallel=True):
    import numpy as np

    def gen():
        while True:
            yield tuple(
                np.random.uniform(low, high, size=s).astype('float32')
                for s in shapes)

    r = _PyReader(shapes=shapes,
                  dtypes=['float32'] * len(shapes),
                  lod_levels=lod_levels)
    r.decorate_paddle_reader(gen)
    return r


class Preprocessor(object):
    """reference layers/io.py:Preprocessor — a reader-to-reader transform
    written with graph ops.

    The reference builds a sub-block of ops consuming the reader's slots
    and re-emits a transformed reader (preprocessor op, reader_op.h).
    Here the ops appended inside `block()` are captured from the main
    program and evaluated per sample batch through the lowering registry,
    so the SAME op set that would run on device transforms the host
    stream; the wrapped reader's __call__/_gen yields transformed slots.
    """

    _instance_counter = 0

    def __init__(self, reader, name=None):
        self.reader = reader
        self.sub_program = None
        self._inputs = None
        self._outputs = None

    def block(self):
        import contextlib

        @contextlib.contextmanager
        def _blk():
            from ..framework import (default_main_program,
                                     default_startup_program)
            blk = default_main_program().global_block()
            sblk = default_startup_program().global_block()
            start = len(blk.ops)
            s_start = len(sblk.ops)
            pre_vars = set(blk.vars)
            pre_svars = set(sblk.vars)
            try:
                yield self
            except BaseException:
                # failed block: remove everything it created — main ops,
                # main vars, and any startup initializers/params
                del blk.ops[start:]
                for n in [n for n in blk.vars if n not in pre_vars]:
                    del blk.vars[n]
                del sblk.ops[s_start:]
                for n in [n for n in sblk.vars if n not in pre_svars]:
                    del sblk.vars[n]
                raise
            self._captured_ops = list(blk.ops[start:])
            # host-side transform ops never stay in the main program;
            # temp vars they produced go too. Parameters (and their
            # startup initializers) STAY — the stream reads their scope
            # values, which the startup program populates.
            del blk.ops[start:]
            for n in [n for n in blk.vars if n not in pre_vars
                      and not getattr(blk.vars[n], 'persistable', False)]:
                del blk.vars[n]
            self._install()
        return _blk()

    def inputs(self):
        self._inputs = read_file(self.reader)
        return self._inputs

    def outputs(self, *outs):
        self._outputs = outs

    def _install(self):
        if self._inputs is None:
            raise ValueError('Preprocessor.block must call inputs()')
        if not self._outputs:
            raise ValueError('Preprocessor.block must call outputs(...)')
        import numpy as np

        import jax

        from .. import lowering
        from ..executor import global_scope
        from ..lowering import Ctx

        in_names = [v.name for v in self._inputs]
        out_names = [v.name for v in self._outputs]
        ops = self._captured_ops
        inner = self.reader._gen

        # names read by the block but produced neither by the reader nor
        # by an earlier block op: parameters / pre-existing vars, resolved
        # from the scope at stream time
        produced = set(in_names)
        external, seen_ext = [], set()
        for op in ops:
            for vs in op.inputs.values():
                for v in vs:
                    if v.name not in produced and v.name not in seen_ext:
                        external.append((op.type, v.name))
                        seen_ext.add(v.name)
            for vs in op.outputs.values():
                for v in vs:
                    produced.add(v.name)

        in_ranks = [len(v.shape) for v in self._inputs]
        inst = Preprocessor._instance_counter
        Preprocessor._instance_counter += 1
        epoch = [0]

        def gen():
            # distinct stream per epoch (each reader() call) and per
            # Preprocessor instance, deterministic across runs
            base = jax.random.fold_in(jax.random.key(inst), epoch[0])
            epoch[0] += 1
            for s_idx, sample in enumerate(inner()):
                env = {}
                for n, s, rank in zip(in_names, sample, in_ranks):
                    a = np.asarray(s)
                    if a.ndim == rank - 1:
                        a = a[None]  # per-sample slot: add the batch axis
                    env[n] = lowering.jnp.asarray(a)
                for op_type, name in external:
                    val = global_scope()._chain_get(name)
                    if val is None:
                        raise NameError(
                            'Preprocessor op %r reads %r, which is neither '
                            'a reader slot, a block-produced var, nor in '
                            'the scope (run the startup program first?)'
                            % (op_type, name))
                    env[name] = val
                # distinct randomness per sample (augmentation), train mode
                key = jax.random.fold_in(base, s_idx)
                for i, op in enumerate(ops):
                    lowering.run_op(op, env, Ctx(key, i, is_test=False))
                yield tuple(np.asarray(env[n]) for n in out_names)

        self.reader._gen = gen


def load(out, file_path, load_as_fp16=None):
    """Load one tensor from file into var (reference layers/io.py:load)."""
    import numpy as np
    from ..executor import global_scope
    import jax.numpy as jnp
    arr = np.load(file_path + '.npy') if not file_path.endswith('.npy') else np.load(file_path)
    global_scope().vars[out.name] = jnp.asarray(arr)
    return out
