"""Learning-rate schedules built from graph ops.

Parity: reference layers/learning_rate_scheduler.py — each schedule appends
ops (driven by the persistable @LR_DECAY_COUNTER@ step var) that compute the
lr value consumed by the optimizer update ops; everything stays inside the
one fused XLA step.
"""
import math

from ..framework import default_main_program, ROLE_LRSCHED
from ..layer_helper import LayerHelper
from ..initializer import Constant
from . import tensor
from . import ops
from . import control_flow

__all__ = [
    'exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
    'polynomial_decay', 'piecewise_decay', 'noam_decay', 'append_LARS',
]


def _decay_step_counter(begin=0):
    """Persistable global step, incremented once per run (reference
    layers/learning_rate_scheduler.py:_decay_step_counter)."""
    helper = LayerHelper('global_step_counter')
    counter_name = '@LR_DECAY_COUNTER@'
    blk = helper.main_program.global_block()
    if counter_name in blk.vars:
        counter = blk.vars[counter_name]
    else:
        counter = helper.create_global_variable(
            name=counter_name, dtype='float32', shape=[1], persistable=True)
        helper.set_variable_initializer(counter,
                                        Constant(value=float(begin - 1)))
    helper.append_op(type='increment', inputs={'X': [counter]},
                     outputs={'Out': [counter]},
                     attrs={'step': 1.0, 'op_role': ROLE_LRSCHED},
                     infer_shape=False)
    counter.stop_gradient = True
    return counter


def noam_decay(d_model, warmup_steps):
    """lr = d_model^-0.5 * min(step^-0.5, step * warmup^-1.5)
    (reference + Transformer paper)."""
    global_step = _decay_step_counter(1)
    a = global_step ** -0.5
    b = (warmup_steps ** -1.5) * global_step
    lr_value = (d_model ** -0.5) * control_flow.min_(a, b)
    return lr_value


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return ops.scale(_pow_scalar_base(decay_rate, div_res),
                     scale=float(learning_rate))


def _pow_scalar_base(base, exponent_var):
    """base ** exponent_var via exp(log(base) * e)."""
    return ops.exp(ops.scale(exponent_var, scale=math.log(float(base))))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    return ops.scale(ops.exp(ops.scale(div_res, scale=-float(decay_rate))),
                     scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    global_step = _decay_step_counter()
    div_res = global_step / float(decay_steps)
    if staircase:
        div_res = ops.floor(div_res)
    denom = ops.scale(div_res, scale=float(decay_rate), bias=1.0)
    return ops.scale(ops.reciprocal(denom), scale=float(learning_rate))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    global_step = _decay_step_counter()
    if cycle:
        div_res = ops.ceil(global_step / float(decay_steps))
        zero = tensor.fill_constant(shape=[1], dtype='float32', value=0.0)
        one = tensor.fill_constant(shape=[1], dtype='float32', value=1.0)
        # when step == 0, div_res should be 1
        div_res = control_flow.max_(div_res, one)
        decay_steps_var = ops.scale(div_res, scale=float(decay_steps))
        frac = global_step / decay_steps_var
    else:
        capped = control_flow.min_(
            global_step,
            tensor.fill_constant(shape=[1], dtype='float32',
                                 value=float(decay_steps)))
        frac = ops.scale(capped, scale=1.0 / float(decay_steps))
    base = ops.scale(frac, scale=-1.0, bias=1.0)  # (1 - t)
    poly = ops.pow(base, factor=float(power))
    return ops.scale(poly, scale=float(learning_rate - end_learning_rate),
                     bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    """Step-wise lr (reference uses a Switch block; here expressed with
    masked sums, which lowers to pure XLA select — no control flow)."""
    assert len(boundaries) + 1 == len(values)
    global_step = _decay_step_counter()
    lr = tensor.fill_constant(shape=[1], dtype='float32', value=float(values[-1]))
    # lr = sum_i value_i * [b_{i-1} <= step < b_i]
    pieces = []
    prev = None
    for i, b in enumerate(boundaries):
        bound = tensor.fill_constant(shape=[1], dtype='float32', value=float(b))
        below = tensor.cast(control_flow.less_than(global_step, bound), 'float32')
        if prev is None:
            indicator = below
        else:
            indicator = below - prev
        pieces.append(ops.scale(indicator, scale=float(values[i])))
        prev = below
    above = ops.scale(prev, scale=-1.0, bias=1.0)
    pieces.append(ops.scale(above, scale=float(values[-1])))
    return tensor.sums(pieces)


def append_LARS(params_grads, learning_rate, weight_decay):
    """LARS per-layer adaptive lr (reference layers/learning_rate_scheduler.py
    :append_LARS)."""
    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return grad_norm + param_norm
        return grad_norm + weight_decay * param_norm

    outs = []
    for param, grad in params_grads:
        param_lr = param.optimize_attr['learning_rate']
        param_norm = ops.sqrt(ops.mean(ops.square(param)))
        grad_norm = ops.sqrt(ops.mean(ops.square(grad)))
        decayed_lr = ops.scale(
            param_norm / _balanced_weight(param_norm, grad_norm),
            scale=float(learning_rate * param_lr))
        outs.append(decayed_lr)
    return outs
