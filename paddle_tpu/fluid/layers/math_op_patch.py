"""Operator overloads on Variable. Parity: reference layers/math_op_patch.py."""
from ..framework import Variable
from ..layer_helper import LayerHelper
from .. import unique_name

__all__ = ['monkey_patch_variable']


def monkey_patch_variable():
    def unique_tmp_name():
        return unique_name.generate("tmp")

    def safe_get_dtype(var):
        return var.dtype

    def create_scalar_var(block, value, dtype, shape=()):
        tmp_name = unique_tmp_name()
        var = block.create_var(name=tmp_name, shape=shape, dtype=dtype)
        block.append_op(type="fill_constant", outputs={'Out': [var]},
                        attrs={'dtype': var.dtype, 'shape': list(shape),
                               'value': float(value)}, infer_shape=False)
        var.stop_gradient = True
        return var

    def astype(self, dtype):
        block = self.block
        out = block.create_var(name=unique_tmp_name(), dtype=dtype, shape=None)
        block.append_op(type="cast", inputs={"X": [self]}, outputs={"Out": [out]},
                        attrs={"in_dtype": self.dtype, "out_dtype": out.dtype})
        return out

    def _elemwise_method_creator_(method_name, op_type, reverse=False,
                                  scalar_method=None):
        def __impl__(self, other_var):
            block = self.block
            if isinstance(other_var, (int, float)):
                if scalar_method is not None:
                    return scalar_method(self, other_var)
                other_var = create_scalar_var(block, other_var,
                                              safe_get_dtype(self))
            lhs, rhs = self, other_var
            if reverse:
                lhs, rhs = rhs, lhs
            out = block.create_var(name=unique_tmp_name(), dtype=lhs.dtype,
                                   shape=None)
            block.append_op(type=op_type, inputs={'X': [lhs], 'Y': [rhs]},
                            outputs={'Out': [out]}, attrs={'axis': -1})
            return out
        __impl__.__name__ = method_name
        return __impl__

    def _scale_method(op):
        def impl(self, scalar):
            from . import ops
            if op == 'add':
                return ops.scale(self, scale=1.0, bias=float(scalar))
            if op == 'sub':
                return ops.scale(self, scale=1.0, bias=-float(scalar))
            if op == 'rsub':
                return ops.scale(self, scale=-1.0, bias=float(scalar))
            if op == 'mul':
                return ops.scale(self, scale=float(scalar))
            if op == 'div':
                return ops.scale(self, scale=1.0 / float(scalar))
            raise ValueError(op)
        return impl

    Variable.astype = astype
    Variable.__add__ = _elemwise_method_creator_(
        "__add__", "elementwise_add", scalar_method=_scale_method('add'))
    Variable.__radd__ = _elemwise_method_creator_(
        "__radd__", "elementwise_add", scalar_method=_scale_method('add'))
    Variable.__sub__ = _elemwise_method_creator_(
        "__sub__", "elementwise_sub", scalar_method=_scale_method('sub'))
    Variable.__rsub__ = _elemwise_method_creator_(
        "__rsub__", "elementwise_sub", reverse=True,
        scalar_method=_scale_method('rsub'))
    Variable.__mul__ = _elemwise_method_creator_(
        "__mul__", "elementwise_mul", scalar_method=_scale_method('mul'))
    Variable.__rmul__ = _elemwise_method_creator_(
        "__rmul__", "elementwise_mul", scalar_method=_scale_method('mul'))
    Variable.__div__ = _elemwise_method_creator_(
        "__div__", "elementwise_div", scalar_method=_scale_method('div'))
    Variable.__truediv__ = Variable.__div__
    Variable.__rdiv__ = _elemwise_method_creator_(
        "__rdiv__", "elementwise_div", reverse=True)
    Variable.__rtruediv__ = Variable.__rdiv__
    Variable.__pow__ = _elemwise_method_creator_("__pow__", "elementwise_pow")
    Variable.__eq__ = _elemwise_method_creator_("__eq__", "equal")
    Variable.__ne__ = _elemwise_method_creator_("__ne__", "not_equal")
    Variable.__lt__ = _elemwise_method_creator_("__lt__", "less_than")
    Variable.__le__ = _elemwise_method_creator_("__le__", "less_equal")
    Variable.__gt__ = _elemwise_method_creator_("__gt__", "greater_than")
    Variable.__ge__ = _elemwise_method_creator_("__ge__", "greater_equal")
    Variable.__neg__ = lambda self: _scale_method('rsub')(self, 0.0)
    Variable.__hash__ = lambda self: hash(id(self))


monkey_patch_variable()
