"""fluid.layers — parity with reference python/paddle/fluid/layers/."""
from . import nn
from .nn import *  # noqa: F401,F403
from . import ops
from .ops import *  # noqa: F401,F403
from . import tensor
from .tensor import *  # noqa: F401,F403
from . import control_flow
from .control_flow import *  # noqa: F401,F403
from . import io
from .io import *  # noqa: F401,F403
from . import device  # noqa: F401
from . import math_op_patch  # noqa: F401 (patches Variable operators)
from . import detection
from .detection import *  # noqa: F401,F403
from . import metric_op
from .metric_op import *  # noqa: F401,F403
from . import learning_rate_scheduler
from .learning_rate_scheduler import *  # noqa: F401,F403
from . import layer_function_generator
from .layer_function_generator import *  # noqa: F401,F403

__all__ = []
__all__ += layer_function_generator.__all__
__all__ += nn.__all__
__all__ += ops.__all__
__all__ += tensor.__all__
__all__ += control_flow.__all__
__all__ += io.__all__
__all__ += detection.__all__
__all__ += metric_op.__all__
__all__ += learning_rate_scheduler.__all__
