"""Thread-local default scope stack.

Parity: reference python/paddle/fluid/default_scope_funcs.py — a
thread-local stack of Scopes with enter/leave local scope helpers and a
`scoped_function` runner. Backed by our Python Scope (executor.py) instead
of the reference's C++ core.Scope; `var` creates-or-gets a slot holder in
the current scope.
"""
import threading

from .executor import Scope

__all__ = [
    'get_cur_scope', 'enter_local_scope', 'leave_local_scope', 'var',
    'find_var', 'scoped_function'
]

_tl = threading.local()


def _stack():
    if not hasattr(_tl, 'scopes') or not _tl.scopes:
        _tl.scopes = [Scope()]
    return _tl.scopes


def get_cur_scope():
    """The innermost scope on this thread's stack."""
    return _stack()[-1]


def enter_local_scope():
    """Push a child scope (its lookups fall back to the parent)."""
    child = get_cur_scope().new_scope()
    _stack().append(child)
    return child


def leave_local_scope():
    """Pop the innermost scope; the root scope is never popped."""
    s = _stack()
    if len(s) > 1:
        s.pop()


def var(name):
    """Create (or fetch) variable `name` in the current scope; returns a
    holder with the reference Variable-like get/set surface."""
    scope = get_cur_scope()
    if name not in scope.vars:
        scope.vars[name] = None
    return _Holder(scope, name)


def find_var(name):
    """Find `name` walking the scope chain (innermost outward)."""
    scope = get_cur_scope()
    while scope is not None:
        if name in scope.vars:
            return _Holder(scope, name)
        scope = scope.parent
    return None


class _Holder(object):
    def __init__(self, scope, name):
        self._scope = scope
        self._name = name

    def get(self):
        return self._scope.vars[self._name]

    def set(self, value):
        self._scope.vars[self._name] = value

    def name(self):
        return self._name


def scoped_function(func):
    """Run `func` inside a fresh local scope (popped afterwards even on
    error)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
