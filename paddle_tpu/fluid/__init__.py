"""paddle_tpu.fluid — the Fluid-compatible TPU-native API.

Parity: reference python/paddle/fluid/__init__.py.
"""
from . import core
from . import framework
from .framework import Program, Operator, Parameter, Variable, \
    default_startup_program, default_main_program, program_guard, \
    name_scope, device_guard, get_var
from . import executor
from .executor import Executor, global_scope, scope_guard, _switch_scope, \
    Scope, anomaly_guard
from . import layers
from . import initializer
from . import optimizer
from . import backward
from .backward import append_backward
from . import regularizer
from . import clip
from .clip import ErrorClipByValue, GradientClipByValue, GradientClipByNorm, \
    GradientClipByGlobalNorm
from . import nets
from . import io
from . import evaluator
from . import metrics
from . import average
from .param_attr import ParamAttr, WeightNormParamAttr
from .data_feeder import DataFeeder
from .lod_tensor import LoDTensor, LoDTensorArray, create_lod_tensor, \
    create_random_int_lodtensor
# API parity re-export (reference fluid/__init__.py imports it by name);
# the patch itself is applied as math_op_patch's import side effect
from .layers.math_op_patch import monkey_patch_variable
from . import unique_name
from . import amp
from . import analysis
from .analysis import ProgramVerifyError
from . import passes
from . import annotations
from . import concurrency
from . import default_scope_funcs
from . import graphviz
from . import net_drawer
from . import recordio_writer
from .concurrency import (Go, make_channel, channel_send, channel_recv,
                          channel_close, Select)
from . import contrib
from . import profiler
from . import debugger
from .core import CPUPlace, TPUPlace, CUDAPlace, CUDAPinnedPlace
from .parallel_executor import ParallelExecutor, ExecutionStrategy, BuildStrategy
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig, \
    InferenceTranspiler, PipelineTranspiler, SequenceParallelTranspiler, \
    TensorParallelTranspiler, memory_optimize, release_memory
from . import trainer
from .trainer import Trainer, BeginEpochEvent, EndEpochEvent, \
    BeginStepEvent, EndStepEvent, CheckpointConfig
from . import inferencer
from .inferencer import Inferencer

Tensor = LoDTensor

__all__ = framework.__all__ + executor.__all__ + transpiler.__all__ + \
    trainer.__all__ + inferencer.__all__ + [
    'io', 'initializer', 'layers', 'transpiler', 'nets', 'optimizer',
    'learning_rate_decay', 'backward', 'regularizer', 'LoDTensor',
    'LoDTensorArray',
    'CPUPlace', 'TPUPlace', 'CUDAPlace', 'CUDAPinnedPlace', 'Tensor',
    'ParamAttr', 'WeightNormParamAttr', 'DataFeeder', 'clip', 'profiler',
    'unique_name',
]


def __bootstrap__():
    return True
