"""Device places and low-level shims.

Parity: reference paddle/fluid/platform/place.h (CPUPlace/CUDAPlace) and the
pybind `core` module (python/paddle/fluid/__init__.py imports `core`).
TPU-first: `TPUPlace` replaces CUDAPlace as the accelerator place; both map to
a jax.Device. A Place only selects which jax device backs Scope arrays and
where jitted programs run — kernels themselves are XLA-compiled, not per-op.
"""
import numpy as np

import jax


class Place(object):
    _platforms = ()

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def jax_device(self):
        devs = [d for d in jax.devices() if d.platform in self._platforms]
        if not devs:
            devs = jax.devices('cpu')
        return devs[min(self.device_id, len(devs) - 1)]


class CPUPlace(Place):
    _platforms = ('cpu',)

    def __init__(self):
        super(CPUPlace, self).__init__(0)


class TPUPlace(Place):
    """The accelerator place (reference: platform::CUDAPlace)."""
    # 'axon' is the tunneled single-chip TPU platform in this environment.
    _platforms = ('tpu', 'axon')


# Alias so code written against the reference's GPU API keeps working.
CUDAPlace = TPUPlace
CUDAPinnedPlace = CPUPlace


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return any(d.platform in ('tpu', 'axon') for d in jax.devices())


def get_tpu_device_count():
    return len([d for d in jax.devices() if d.platform in ('tpu', 'axon')])


# Fluid VarDesc dtype enum compatibility (reference: framework.proto VarType).
class VarDesc(object):
    class VarType(object):
        BOOL = 0
        INT16 = 1
        INT32 = 2
        INT64 = 3
        FP16 = 4
        FP32 = 5
        FP64 = 6
        LOD_TENSOR = 7
        SELECTED_ROWS = 8
        FEED_MINIBATCH = 9
        FETCH_LIST = 10
        STEP_SCOPES = 11
        LOD_RANK_TABLE = 12
        LOD_TENSOR_ARRAY = 13
        PLACE_LIST = 14
        READER = 15
        UINT8 = 20
        BF16 = 22
        RAW = 17


_DTYPE_ENUM_TO_NP = {
    VarDesc.VarType.BOOL: np.bool_,
    VarDesc.VarType.INT16: np.int16,
    VarDesc.VarType.INT32: np.int32,
    VarDesc.VarType.INT64: np.int64,
    VarDesc.VarType.FP16: np.float16,
    VarDesc.VarType.FP32: np.float32,
    VarDesc.VarType.FP64: np.float64,
    VarDesc.VarType.UINT8: np.uint8,
}


def convert_dtype(dtype):
    """Normalize str / np.dtype / VarType enum to a canonical dtype string."""
    import jax.numpy as jnp
    if isinstance(dtype, int):
        dtype = _DTYPE_ENUM_TO_NP[dtype]
    if dtype == 'bfloat16' or dtype is jnp.bfloat16:
        return 'bfloat16'
    return np.dtype(dtype).name


def __getattr__(name):
    # Scope lives in executor.py (it owns the var-store design), but the
    # reference exposes it as `fluid.core.Scope` (pybind core module) and
    # reference book code instantiates it through that path — lazy alias
    # to avoid a core <-> executor import cycle.
    if name == 'Scope':
        from .executor import Scope
        return Scope
    raise AttributeError('module %r has no attribute %r'
                         % (__name__, name))
