"""The compiled-step artifact: ONE first-class object per (program,
feed-signature, fetch-set) owning everything the four step drivers need.

The runtime used to assemble lower -> shard -> donate -> dispatch ->
fetch four separate ways (`Executor.run`, `run_bundle`, `StepHandle.step`,
the serving dispatch), with `state_dict` bolted on the side. This module
is the convergence point (ROADMAP item 5; the SNIPPETS.md pjit exemplar —
one donation_vector/in_shardings/out_shardings computation reused by
every caller): a `StepArtifact` owns

  * the optimized program + lowered op walk (the jittable step body);
  * the memory/donation plan (fluid.passes.memplan) — which persistables
    donate, which ride read-only, which re-emerge as outputs;
  * the NamedSharding trees (GSPMD annotation path) pinned as the step's
    in/out layout fixed point;
  * the RNG-stream policy (op_seq-stamped per-op streams; bundled scans
    re-derive per-step keys from scanned uint32 seeds);
  * the feed/fetch signature (`feed_names`/`fetch_names` + the
    feed-signature tuples cache keys and AOT manifests are built from);
  * the `state_dict` seam (`state_names`/`state_dict` — the placement-
    true persistable view sharded checkpointing consumes);
  * every jitted entry point compiled from it: the unbundled step and
    one K-scan per bundle length (`signatures()` enumerates them).

The four drivers stay thin: `Executor.run` dispatches one step,
`run_bundle` scans K steps over the SAME body, `StepHandle` pins a
donation view for hot loops, and the serving engines drive warmed
signatures through the same cache. All of them build through
`Executor._prepare`, which resolves one artifact per cache key — the
driver-equivalence drill in tests/test_step_artifact.py asserts the
shared entry and bit-identical fetches.

`pin_state` is the donate-exactly-once contract: persistable state is
committed to its device placement BEFORE the first jitted call, so the
first call's argument signature (committed device arrays) is identical
to every later call's (donated outputs come back committed) and each
entry point compiles exactly once — the PR 4 "warm twice" run_bundle
wart was precisely this committedness flip re-specializing the scan on
its second call.

Migration note (docs/architecture.md): this class was
`fluid.executor._CompiledStep`; that name remains importable as an
alias, but new code should reach it here.
"""
import os

import numpy as np

import jax
import jax.numpy as jnp

from . import lowering
from .lowering import SeqValue, Ctx

__all__ = ['StepArtifact', 'program_fingerprint', 'stable_signature',
           'aot_manifest', 'write_aot', 'read_aot', 'aot_check',
           'AOT_MANIFEST', 'AOT_CACHE_DIR']


def _is_annotated(program):
    """True for a Program on the first-class GSPMD annotation path:
    a `set_mesh()` spec and no legacy transpiler `_dist_config` (the
    transpilers keep their own mesh build until fully retired)."""
    return (getattr(program, '_mesh_axes', None) is not None
            and getattr(program, '_dist_config', None) is None)


def _feed_signature(name, val):
    if isinstance(val, SeqValue):
        return (name, 'seq', tuple(val.data.shape), str(val.data.dtype))
    arr = np.asarray(val) if not hasattr(val, 'shape') else val
    return (name, tuple(arr.shape), str(arr.dtype))


class StepArtifact(object):
    """One lowered+jitted (program, feed-sig, fetch) combination."""

    def __init__(self, program, block, feed_names, fetch_names, persist_in,
                 amp=False, platform='cpu', persist_shardings=None,
                 mesh=None, guard=False, jit_shardings=None):
        self.program = program
        self.amp = amp
        self.platform = platform
        self.mesh = mesh
        # in-graph anomaly guard (see anomaly_guard()): only meaningful on
        # training steps — without an autodiff op there are no gradients
        # to check and no optimizer update to skip
        self.guard = bool(guard)
        # GPipe region from PipelineTranspiler: only active when a mesh
        # with the pp axis exists; otherwise the stamped ops run
        # sequentially (identical semantics, which tests compare against)
        pipe = getattr(program, '_pipeline_config', None)
        self.pipe = (pipe if pipe is not None and mesh is not None
                     and pipe['axis'] in getattr(mesh, 'shape', {})
                     else None)
        if self.pipe is not None and 'sp' in getattr(mesh, 'shape', {}):
            # backstop for programs whose configs were hand-assembled or
            # clone-carried past the transpilers' own validation: stage
            # bodies run sequence-local under sp (see pipeline_transpiler)
            from .transpiler.pipeline_transpiler import (
                validate_sp_sequence_local)
            lo0, hi0 = self.pipe['stage0']
            validate_sp_sequence_local(block.ops[lo0:hi0])
        if self.pipe is not None:
            lo_r, hi_r = self.pipe['region']
            internal = set()
            for op in block.ops[lo_r:hi_r]:
                internal.update(op.output_arg_names)
            internal.discard(self.pipe['output_var'])
            bad = internal & set(fetch_names)
            if bad:
                raise ValueError(
                    'cannot fetch %r: produced inside the pipeline region, '
                    'which runs as one GPipe call — fetch the stage output '
                    '%r or run the program untranspiled'
                    % (sorted(bad), self.pipe['output_var']))
        self.use_remat = bool(getattr(program, '_use_remat', False))
        # name -> NamedSharding: enforced on the step's outputs so
        # mesh-placed state (ZeRO accumulators, tp weights) STAYS sharded
        # inside the compiled module instead of relying on propagation
        self.persist_shardings = dict(persist_shardings or {})
        ops = list(block.ops)
        self.ops = ops
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.persist_in = list(persist_in)
        # set by Executor._prepare after construction: the placed-feed
        # signature tuples this artifact was keyed on, the short cache-key
        # id it reports under, and the SOURCE program (self.program may be
        # the optimized clone) — the inputs of stable_signature()
        self._feed_sig = None
        self._key_id = None
        self._source_program = None
        self._stable_sig = None
        ad_idxs = [i for i, op in enumerate(ops) if op.type == 'autodiff']
        assert len(ad_idxs) <= 1, "at most one append_backward per program"
        self.ad_idx = ad_idxs[0] if ad_idxs else None
        for op in (o for blk in program.blocks for o in blk.ops):
            # loud inertness check (docs/embedding.md): a TRAINING step
            # whose lookup was built for the distributed wire (annotated
            # table, is_distributed) compiling WITHOUT a mesh that
            # declares its axis silently degrades to a replicated dense
            # gather — the pserver-era failure mode this subsystem
            # exists to replace. Once per compiled key, like every other
            # _prepare-time diagnostic. Inference programs are exempt:
            # the documented export seam (gather_table + set_mesh(None),
            # docs/serving.md) runs the for_test clone dense-after-
            # gather on purpose.
            if (self.ad_idx is not None and op.type == 'lookup_table'
                    and op.attrs.get('is_distributed')
                    and op.attrs.get('dist_axis') is not None
                    and (mesh is None or op.attrs['dist_axis']
                         not in getattr(mesh, 'shape', {}))):
                import warnings
                warnings.warn(
                    "embedding(is_distributed=True) on table %r is "
                    "annotated for mesh axis %r but the step compiles "
                    "against %s — the lookup runs as a replicated dense "
                    "gather. Declare Program.set_mesh({%r: N, ...}) to "
                    "shard it (docs/embedding.md)."
                    % (op.inputs['W'][0].name, op.attrs['dist_axis'],
                       'no mesh' if mesh is None
                       else 'mesh axes %r' % sorted(mesh.shape),
                       op.attrs['dist_axis']), UserWarning)
        self.sparse_plan = self._sparse_embedding_plan(program)
        # Donation/memory plan (fluid.passes.memplan): which persistables
        # the ops actually WRITE decides donation. A mutating step
        # (training: optimizer updates, BN stats, LR counters) donates
        # EXACTLY its written buffers — in-place HBM updates, re-exposed
        # as outputs — while read-only persistable inputs (frozen
        # weights, inference BN stats) are neither donated nor carried
        # through the module's output list: their scope buffers stay
        # valid, and XLA stops paying a passthrough copy per step. A
        # fully read-only step (inference) donates nothing at all:
        # donation there would invalidate the param buffers under
        # concurrent runs (the serving engine / multi-threaded
        # Predictors). The plan derives from the SAME write-set
        # fluid.analysis verifies, so the static donation-safety pass
        # cross-checks THIS decision, not a copy of it; run_bundle and
        # the serving warmup consume the same plan object.
        from .passes import memory_plan
        self.plan = memory_plan(program)
        self.mutates_persist = self.plan.donates
        self.donate_names = self.plan.donate_names(self.persist_in)
        self.readonly_names = self.plan.readonly_names(self.persist_in)
        self.persist_out = self.plan.persist_out()
        # GSPMD annotation path (docs/parallel.md): explicit jit in/out
        # sharding trees derived by the memory plan from the ACTUAL
        # placed shardings — donated inputs and persistable outputs
        # share one NamedSharding object per name, so the compiled
        # step's state layout is a fixed point (no inter-step
        # resharding, no involuntary rematerialization at scan/carry
        # boundaries). jit_shardings: {'persist': name->sharding|None,
        # 'feed': name->sharding|None, 'specs': name->annotation}.
        self._annot_sh = None
        if jit_shardings is not None and mesh is not None:
            from jax.sharding import NamedSharding as _NS, \
                PartitionSpec as _PS
            repl = _NS(mesh, _PS())
            don_sh, ro_sh, out_sh = self.plan.sharding_plan(
                self.persist_in, jit_shardings['persist'])
            for n in out_sh:
                if out_sh[n] is None and n not in jit_shardings['persist']:
                    # persistable the step CREATES (startup programs):
                    # its annotation decides the birth layout
                    spec = jit_shardings['specs'].get(n)
                    out_sh[n] = _NS(mesh, _PS(*spec)) if spec else repl
            self._annot_sh = (don_sh, ro_sh,
                              dict(jit_shardings['feed']), out_sh)

        run_range = self._run_ops

        def step(donated, readonly, feed, key):
            env = dict(readonly)
            env.update(donated)
            env.update(feed)
            health = None
            if self.ad_idx is None:
                run_range(env, 0, len(ops), key)
            else:
                ad = ops[self.ad_idx]
                pnames, gnames, trainable, base, taps = \
                    self._grad_setup(env, ad)
                fwd = self._make_fwd(base, ad, key, taps=taps)
                if self.use_remat:
                    # memory_optimize(): recompute forward activations in
                    # the backward pass instead of saving them (the TPU
                    # lever matching the reference's liveness buffer reuse).
                    fwd = jax.checkpoint(fwd)
                grads, env = jax.grad(fwd, has_aux=True)(trainable)
                self._apply_grads(grads, env, ad, pnames, gnames)
                if self.guard:
                    health = self._step_health(env, ad, pnames, gnames)
                run_range(env, self.ad_idx + 1, len(ops), key)
            fetches = [env[n] for n in self.fetch_names]
            new_persist = {n: env[n] for n in self.persist_out if n in env}
            if health is not None:
                self._select_healthy(health['healthy'], new_persist,
                                     donated)
            for n, sh in self.persist_shardings.items():
                if n in new_persist and not isinstance(new_persist[n], SeqValue):
                    new_persist[n] = jax.lax.with_sharding_constraint(
                        new_persist[n], sh)
            return fetches, new_persist, health

        self._step_fn = step  # pure, un-jitted, split (donated, readonly)
        # the donation vector comes from the memory plan for BOTH paths
        # (one definition: donate exactly the written-persistables arg)
        donate = self.plan.donate_argnums(self.persist_in)
        if self._annot_sh is not None:
            don_sh, ro_sh, feed_sh, out_sh = self._annot_sh
            self._jitted = jax.jit(
                step,
                in_shardings=(don_sh, ro_sh, feed_sh, None),
                out_shardings=(None, out_sh, None),
                donate_argnums=donate)
        else:
            self._jitted = jax.jit(step, donate_argnums=donate)
        # K -> jitted K-step lax.scan over the SAME step body (run_bundle)
        self._bundles = {}

    def _step(self, persist, feed, key):
        """Un-jitted step over a FULL persist dict (the pre-plan
        signature; export_compiled and the transpiler drills trace
        through this)."""
        donated, readonly = self.plan.split(persist)
        return self._step_fn(donated, readonly, feed, key)

    def bundle(self, K):
        """The K-step bundled executable: ONE jitted lax.scan whose body is
        the exact `step` the unbundled path jits — one device dispatch and
        one host round-trip per K steps instead of per step. Carry is the
        persist dict (donated, so persistables stay in-place in HBM across
        ALL K inner steps); xs are the stacked feeds plus per-step uint32
        seeds — the RNG key is created INSIDE the body from the same seed
        integer run() would pass to jax.random.key on the host, so
        per-step randomness is bit-identical to K unbundled runs. ys are
        the per-step fetches (stacked on a leading K axis) and, when the
        anomaly guard is armed, the per-step health vectors (rollback
        already applied in-graph by `step`, per inner step)."""
        K = int(K)
        fn = self._bundles.get(K)
        if fn is None:
            step = self._step_fn

            def bundled(donated, readonly, feeds, seeds):
                # carry = the plan's donated (written) set only; the
                # read-only persistables ride along as a plain argument,
                # invariant across the scan
                def body(carry, xs):
                    feed, seed = xs
                    fetches, new_persist, health = step(
                        carry, readonly, feed, jax.random.key(seed))
                    nxt = {n: new_persist.get(n, carry[n]) for n in carry}
                    return nxt, (fetches, health)

                return jax.lax.scan(body, donated, (feeds, seeds))

            donate = self.plan.donate_argnums(self.persist_in)
            if self._annot_sh is not None:
                # same sharding fixed point as the unbundled jit: the
                # scan carry's in- and out-shardings are the SAME
                # objects, feeds gain a leading (scanned) K dim
                from jax.sharding import NamedSharding as _NS, \
                    PartitionSpec as _PS
                don_sh, ro_sh, feed_sh, _out = self._annot_sh
                stacked_sh = {
                    n: (_NS(sh.mesh, _PS(None, *sh.spec))
                        if isinstance(sh, _NS) else None)
                    for n, sh in feed_sh.items()}
                fn = jax.jit(
                    bundled,
                    in_shardings=(don_sh, ro_sh, stacked_sh, None),
                    out_shardings=(don_sh, None),
                    donate_argnums=donate)
            else:
                fn = jax.jit(bundled, donate_argnums=donate)
            self._bundles[K] = fn
        return fn

    # optimizer ops with a SparseRows (SelectedRows-analogue) grad branch
    # in ops_impl/optim_ops.py
    _SPARSE_OPTS = frozenset(['sgd', 'adagrad', 'adam'])

    def _sparse_embedding_plan(self, program):
        """Which embedding tables can take the sparse gradient path.

        Reference: lookup_table_op.cc emits a SelectedRows grad when
        is_sparse=True and sgd/adagrad/adam update only the touched rows.
        Here jax.grad would produce a DENSE vocab-sized @GRAD buffer; for a
        table W we instead differentiate w.r.t. a zero "tap" added to each
        lookup's gathered rows, and hand the optimizer a
        lowering.SparseRows(ids, rows) — the vocab-sized buffer never
        exists (VERDICT r4 item 4). Eligibility (else silent dense
        fallback, bit-identical for SGD since scatter-add is how XLA
        derives the dense grad anyway):
          - every reader of W (except its optimizer op) is a lookup_table
            with is_sparse=True;
          - W@GRAD is consumed by exactly one sgd/adagrad/adam op and
            produced only by autodiff (no clip/regularizer rewriting it),
            is not persistable and not fetched;
          - the step is unsharded (self.mesh is None), OR — the sharded-
            embedding subsystem (docs/embedding.md) — the program is on
            the first-class annotation path and W is row-sharded over a
            mesh axis with every lookup stamped for the distributed wire
            (is_sparse=True + is_distributed=True): the SparseRows grad
            then stays touched-rows-only and the optimizer's row scatter
            partitions per shard, so the dense [vocab, dim] gradient
            never exists on any device. Legacy transpiler meshes keep
            the dense fallback: there the dense grad IS the right thing
            — XLA all-reduces it — and SelectedRows never distributed in
            the reference either.
        Returns {w_name: {'lookups': [(op_idx, ids_name, padding_idx)],
                          'gname': str}}."""
        if self.ad_idx is None:
            return {}
        if self.mesh is not None and not _is_annotated(program):
            return {}
        ad = self.ops[self.ad_idx]
        gnames = dict(zip(ad.attrs['param_names'], ad.attrs['grad_names']))
        persistable = {v.name for v in program.list_vars() if v.persistable}
        readers = {}   # var name -> [op index]
        writers = {}
        for i, op in enumerate(self.ops):
            if i == self.ad_idx:
                continue
            for n in op.input_arg_names:
                readers.setdefault(n, []).append(i)
            for n in op.output_arg_names:
                writers.setdefault(n, []).append(i)
        plan = {}
        for w, gname in gnames.items():
            if self.mesh is not None:
                var = program.global_block().vars.get(w)
                spec = getattr(var, 'sharding', None)
                row = spec[0] if spec else None
                if (row is None or isinstance(row, tuple)
                        or row not in getattr(self.mesh, 'shape', {})):
                    # mesh without a row-sharded annotation: the dense
                    # grad all-reduces; only the sharded-sparse
                    # combination takes the SparseRows path here
                    continue
            lookups = []
            opt_idx = None
            ok = gname not in self.fetch_names and gname not in persistable
            for i in set(readers.get(w, [])):
                op = self.ops[i]
                if (op.type == 'lookup_table' and op.attrs.get('is_sparse')
                        and op.inputs['W'][0].name == w
                        and (self.mesh is None
                             or op.attrs.get('dist_axis') is not None)):
                    lookups.append(
                        (i, op.inputs['Ids'][0].name,
                         op.attrs.get('padding_idx', -1)))
                elif (op.type in self._SPARSE_OPTS and opt_idx is None
                      and any(v.name == gname
                              for v in op.inputs.get('Grad', []))):
                    opt_idx = i
                else:
                    ok = False
            grad_readers = set(readers.get(gname, []))
            grad_writers = set(writers.get(gname, []))
            if (ok and lookups and opt_idx is not None
                    and grad_readers <= {opt_idx} and not grad_writers):
                plan[w] = {'lookups': sorted(lookups), 'gname': gname}
        return plan

    @staticmethod
    def _tap_name(w, op_idx):
        return '%s@SPTAP%d' % (w, op_idx)

    def _grad_setup(self, env, ad):
        """Split env into trainable params vs everything else for jax.grad.

        Sparse-embedding params (self.sparse_plan) are NOT differentiated
        directly: a zero tap per lookup joins `trainable` instead, whose
        gradient is the per-occurrence row gradient (see
        _sparse_embedding_plan). Returns (pnames, gnames, trainable, base,
        taps) where taps maps lookup op index -> (tap name, out var name)
        for _run_ops to inject."""
        pnames = [n for n in ad.attrs['param_names'] if n in env]
        gnames = dict(zip(ad.attrs['param_names'], ad.attrs['grad_names']))
        taps = {}
        sparse_active = {}
        for w, plan in self.sparse_plan.items():
            if w not in env:
                continue
            # ids must be resolvable BEFORE the forward runs to size the
            # zero taps: feed/persist vars only (intermediate id tensors
            # fall back to the dense path)
            if not all(ids in env for _, ids, _ in plan['lookups']):
                continue
            sparse_active[w] = plan
        trainable = {n: env[n] for n in pnames if n not in sparse_active}
        for w, plan in sparse_active.items():
            d = env[w].shape[-1]
            for op_idx, ids_name, _pad in plan['lookups']:
                ids = lowering.data_of(env[ids_name])
                shp = ids.shape[:-1] if (ids.ndim and ids.shape[-1] == 1) \
                    else ids.shape
                op = self.ops[op_idx]
                taps[op_idx] = (self._tap_name(w, op_idx),
                                op.outputs['Out'][0].name)
                trainable[self._tap_name(w, op_idx)] = jnp.zeros(
                    tuple(shp) + (d,), env[w].dtype)
        self._sparse_active = sparse_active
        pnames = [n for n in pnames if n not in sparse_active]
        base = {k: v for k, v in env.items() if k not in trainable}
        return pnames, gnames, trainable, base, taps

    def _make_fwd(self, base, ad, key, taps=None):
        """The differentiable forward closure: trainable -> (loss, env)."""
        def fwd(tr):
            e = dict(base)
            e.update(tr)
            self._run_ops(e, 0, self.ad_idx, key, grad_mode=True,
                          taps=taps)
            loss = e[ad.attrs['loss_name']]
            return jnp.sum(loss.astype(jnp.float32)), e
        return fwd

    def _apply_grads(self, grads, env, ad, pnames, gnames,
                     check_nan_inf=False):
        """Scale/cast gradients into env under their @GRAD names. Shared by
        the jitted step and debug_step so both paths compute identically.
        Sparse-embedding params bind a lowering.SparseRows under their
        @GRAD name instead of a dense vocab-sized buffer."""
        scale = ad.attrs.get('loss_scale', 1.0)
        for n in pnames:
            g = grads[n]
            if scale != 1.0:
                g = g * scale
            g = g.astype(env[n].dtype)
            if check_nan_inf and not bool(jnp.isfinite(g).all()):
                raise FloatingPointError(
                    "NaN/Inf in gradient %r (of parameter %r)"
                    % (gnames[n], n))
            env[gnames[n]] = g
        for w, plan in getattr(self, '_sparse_active', {}).items():
            d = env[w].shape[-1]
            ids_parts, row_parts = [], []
            for op_idx, ids_name, pad in plan['lookups']:
                ids = lowering.data_of(env[ids_name]).astype(
                    jnp.int32).reshape((-1,))
                rows = grads[self._tap_name(w, op_idx)].reshape((-1, d))
                if pad is not None and pad >= 0:
                    # the dense grad's padding_idx row is zeroed by the
                    # lookup rule's w.at[pad].set(0); mirror that here
                    rows = jnp.where((ids == pad)[:, None], 0.0, rows)
                ids_parts.append(ids)
                row_parts.append(rows)
            rows = jnp.concatenate(row_parts, axis=0)
            if scale != 1.0:
                rows = rows * scale
            rows = rows.astype(env[w].dtype)
            if check_nan_inf and not bool(jnp.isfinite(rows).all()):
                raise FloatingPointError(
                    "NaN/Inf in gradient %r (of parameter %r)"
                    % (gnames[w], w))
            env[gnames[w]] = lowering.SparseRows(
                jnp.concatenate(ids_parts, axis=0), rows, env[w].shape)

    def _step_health(self, env, ad, pnames, gnames):
        """Per-step health vector, computed INSIDE the compiled module on
        values the backward pass already produced: finiteness of the loss
        and of every gradient (dense and sparse-row), and the global
        grad-norm. A few fused reductions — no extra launch, no eager
        fallback (contrast debugger.check_nan_inf, the op-by-op eager
        attribution mode)."""
        loss = lowering.data_of(env[ad.attrs['loss_name']])
        loss_finite = jnp.isfinite(loss.astype(jnp.float32)).all()
        grads_finite = jnp.asarray(True)
        sq = jnp.asarray(0.0, jnp.float32)
        names = list(pnames) + list(getattr(self, '_sparse_active', {}))
        for n in names:
            g = env.get(gnames[n])
            if g is None:
                continue
            gl = g.rows if isinstance(g, lowering.SparseRows) \
                else lowering.data_of(g)
            gf = gl.astype(jnp.float32)
            grads_finite = grads_finite & jnp.isfinite(gf).all()
            sq = sq + jnp.sum(gf * gf)
        grad_norm = jnp.sqrt(sq)
        return {'healthy': loss_finite & grads_finite,
                'loss_finite': loss_finite,
                'grads_finite': grads_finite,
                'grad_norm': grad_norm}

    def _select_healthy(self, healthy, new_persist, persist):
        """Step-skip policy (the AMP loss-scaling skip, generalized): when
        the step is unhealthy, every persistable output rolls back to its
        pre-step value via a predicated select, so params / optimizer
        state / BN stats are bit-identical to before the step. Runs inside
        the jitted module; with donation the select aliases in place."""
        for n in list(new_persist):
            old = persist.get(n)
            new = new_persist[n]
            if old is None:
                continue
            if jax.tree_util.tree_structure(old) != \
                    jax.tree_util.tree_structure(new):
                continue  # layout changed this step; nothing to roll back to
            new_persist[n] = jax.tree_util.tree_map(
                lambda a, b: a if getattr(a, 'shape', None) != getattr(
                    b, 'shape', None) else jnp.where(healthy, a, b),
                new, old)

    def _run_ops(self, env, lo, hi, key, grad_mode=False, on_op=None,
                 taps=None):
        """Execute ops [lo, hi); on_op(i, op, seconds, env) — when set, each
        op is synchronized and timed (debug/profiling path, eager only).
        taps: {op_index: (tap_name, out_var_name)} — after the op at
        op_index runs, the zero tap joins its output so jax.grad yields the
        per-row gradient there (sparse embedding path)."""
        pipe = self.pipe
        for i in range(lo, hi):
            if pipe is not None and on_op is None \
                    and pipe['region'][0] <= i < pipe['region'][1]:
                if i == pipe['region'][0]:
                    self._run_pipeline_region(env, key, grad_mode=grad_mode)
                continue  # region ops execute inside pipeline_apply
            op = self.ops[i]
            if op.type == 'autodiff':
                continue
            # RNG stream id: the op's ORIGINAL build index when the
            # optimizer stamped one (passes.OP_SEQ_ATTR) — op removal
            # must never shift another op's dropout mask — else the
            # list position (unoptimized programs, bit-for-bit the old
            # behavior)
            seq = op.attrs.get('op_seq', i)
            if on_op is None:
                lowering.run_op(op, env, Ctx(key, seq, amp=self.amp,
                                             platform=self.platform,
                                             mesh=self.mesh))
            else:
                import time
                t0 = time.perf_counter()
                lowering.run_op(op, env, Ctx(key, seq, amp=self.amp,
                                             platform=self.platform,
                                             mesh=self.mesh))
                outs = [env[v.name] for vs in op.outputs.values()
                        for v in vs if env.get(v.name) is not None]
                jax.block_until_ready(outs)
                on_op(i, op, time.perf_counter() - t0, env)
            if taps is not None and i in taps:
                tname, oname = taps[i]
                v = env[oname]
                env[oname] = lowering.like(
                    v, lowering.data_of(v) + env[tname])
            if grad_mode:
                for vs in op.outputs.values():
                    for v in vs:
                        if v.stop_gradient and v.name in env and env[v.name] is not None:
                            env[v.name] = jax.tree_util.tree_map(
                                jax.lax.stop_gradient, env[v.name])

    def _run_pipeline_region(self, env, key, grad_mode=False):
        with jax.named_scope('pipeline_region_%d' % self.pipe['region'][0]):
            return self._run_pipeline_region_impl(env, key,
                                                  grad_mode=grad_mode)

    def _run_pipeline_region_impl(self, env, key, grad_mode=False):
        """Execute the PipelineTranspiler region as ONE GPipe call.

        Per-stage parameters are stacked [S, ...] on the fly (grad of
        stack = unstack, so jax.grad routes each stage's gradient back to
        its own parameter, and the program's optimizer ops update them
        unchanged); pipeline_apply shards the stack over the pp mesh axis
        and streams n_micro microbatches around the ppermute ring. NOTE:
        the stage RNG key is shared across stages/microbatches, so
        in-stage dropout masks are correlated — acceptable for GPipe
        (dropout is per-activation); tests compare with dropout off.
        """
        cfg = self.pipe
        from .. import parallel
        S, M = cfg['n_stages'], cfg['n_micro']
        x = env[cfg['input_var']]
        if x.shape[0] % M:
            raise ValueError(
                'pipeline n_micro=%d does not divide batch size %d'
                % (M, x.shape[0]))
        extras = tuple(env[n] for n in cfg['extra_names'])
        mb = x.shape[0] // M
        streamed = []
        for n in cfg['extra_stream_names']:
            e = env[n]
            if e.shape[0] != x.shape[0]:
                raise ValueError(
                    'batch-aligned pipeline extra %r has leading dim %d, '
                    'expected the batch size %d' % (n, e.shape[0],
                                                    x.shape[0]))
            streamed.append(e.reshape((M, mb) + e.shape[1:]))
        # Stack each stage's weights [S, ...] and PIN the stack's sharding:
        # dim 0 over the pp axis, trailing dims keeping the per-stage
        # weight's own (tp) spec. Without the constraint GSPMD has to
        # transition from the stacked per-stage shardings to the
        # shard_map's pp layout on its own and falls back to
        # replicate-then-repartition ("Involuntary full rematerialization",
        # MULTICHIP_r04 tail) — a full weight-stack all-gather every step.
        from jax.sharding import NamedSharding, PartitionSpec as _PS
        stacked, stacked_specs = {}, {}
        for j, n0 in enumerate(cfg['param_names'][0]):
            leaves = [env[cfg['param_names'][k][j]] for k in range(S)]
            if self.mesh is not None:
                # pin each element to an explicit replicated layout before
                # stacking: without this GSPMD back-propagates shardings
                # from inside the pipeline shard_map onto the stack and
                # falls back to replicate-then-repartition per step
                # ("Involuntary full rematerialization", MULTICHIP_r04)
                rep = NamedSharding(self.mesh, _PS())
                leaves = [jax.lax.with_sharding_constraint(x, rep)
                          for x in leaves]
            stacked[n0] = jnp.stack(leaves)
            base_sh = self.persist_shardings.get(n0)
            stacked_specs[n0] = (tuple(base_sh.spec)
                                 if base_sh is not None else ())
        mbs = x.reshape((M, mb) + x.shape[1:])
        lo0, hi0 = cfg['stage0']
        stage_ops = self.ops[lo0:hi0]
        extra_names = cfg['extra_stream_names'] + cfg['extra_names']
        input_name, boundary0 = cfg['input_var'], cfg['boundary0']

        # the region body is manual over dp/pp (and sp when composed);
        # mesh-aware lowerings (sp attention) must use per-shard
        # collective bodies on these axes instead of opening a shard_map
        manual = (parallel.pipeline_manual_axes(self.mesh, cfg['axis'])
                  if self.mesh is not None else frozenset())

        def stage_fn(p, xx, *ex):
            sub = dict(zip(extra_names, ex))
            sub.update(p)
            sub[input_name] = xx
            for t, op in enumerate(stage_ops):
                lowering.run_op(op, sub, Ctx(key, lo0 + t, amp=self.amp,
                                             platform=self.platform,
                                             mesh=self.mesh,
                                             manual_axes=manual))
                if grad_mode:
                    # same stop_gradient contract as the sequential path
                    # (_run_ops): frozen vars stay frozen when pipelined
                    for vs in op.outputs.values():
                        for v in vs:
                            if (v.stop_gradient and v.name in sub
                                    and sub[v.name] is not None):
                                sub[v.name] = jax.tree_util.tree_map(
                                    jax.lax.stop_gradient, sub[v.name])
            return sub[boundary0]

        out = parallel.pipeline_apply(stage_fn, stacked, mbs, self.mesh,
                                      axis=cfg['axis'], extras=extras,
                                      extras_streamed=tuple(streamed),
                                      n_virtual=cfg.get('n_virtual', 1),
                                      param_specs=stacked_specs)
        res = out.reshape((-1,) + out.shape[2:])
        if self.mesh is not None:
            # Pin the region boundary to the batch-sharded layout the
            # surrounding (dp/sp-partitioned) ops use. The constraint
            # transposes to ITSELF, so the backward cotangent entering
            # the region carries the same explicit sharding — without it
            # GSPMD has to invent the transition from the downstream
            # layout to the region's microbatched one and falls back to
            # replicate-then-repartition ("Involuntary full
            # rematerialization", MULTICHIP_r05 tail).
            from jax.sharding import NamedSharding as _NS, \
                PartitionSpec as _PS
            entries = [None] * res.ndim
            if 'dp' in self.mesh.shape:
                entries[0] = 'dp'
            if 'sp' in self.mesh.shape and res.ndim >= 2:
                entries[1] = 'sp'
            if any(entries):
                res = jax.lax.with_sharding_constraint(
                    res, _NS(self.mesh, _PS(*entries)))
        env[cfg['output_var']] = res

    def debug_step(self, persist, feed, key, check_nan_inf=False, on_op=None):
        """Eager op-by-op execution: per-op NaN/Inf checks (reference C++
        check_nan_inf, operators/isfinite_op) and per-op wall times for the
        profiler table. Slower than the jitted step by design."""
        hooks = []
        if on_op is not None:
            hooks.append(on_op)
        if check_nan_inf:
            hooks.append(_nan_inf_hook)

        def hook(i, op, dt, env):
            for h in hooks:
                h(i, op, dt, env)

        ops = self.ops
        env = dict(persist)
        env.update(feed)
        health = None
        if self.ad_idx is None:
            self._run_ops(env, 0, len(ops), key, on_op=hook)
        else:
            ad = ops[self.ad_idx]
            pnames, gnames, trainable, base, taps = \
                self._grad_setup(env, ad)
            # eager, hooked forward pass (this is the per-op signal)
            self._run_ops(env, 0, self.ad_idx, key, on_op=hook)
            grads, _ = jax.grad(self._make_fwd(base, ad, key, taps=taps),
                                has_aux=True)(trainable)
            self._apply_grads(grads, env, ad, pnames, gnames,
                              check_nan_inf=check_nan_inf)
            if self.guard:
                # the guard stays armed on the eager path too (profiler
                # hook / debugger active): same health vector, same
                # skip-with-rollback — the jnp ops just run un-jitted
                health = self._step_health(env, ad, pnames, gnames)
            self._run_ops(env, self.ad_idx + 1, len(ops), key, on_op=hook)
        fetches = [env[n] for n in self.fetch_names]
        new_persist = {n: env[n] for n in self.persist_out if n in env}
        if health is not None:
            self._select_healthy(health['healthy'], new_persist, persist)
        return fetches, new_persist, health

    def __call__(self, persist, feed, key):
        donated, readonly = self.plan.split(persist)
        return self._jitted(donated, readonly, feed, key)

    # -- first-class artifact surface ----------------------------------

    def signatures(self):
        """Every jitted entry point this artifact has built: the
        unbundled step plus one ('bundle', K) scan per bundle length.
        Each compiles (or persistent/AOT-deserializes) exactly once —
        the signature set an AOT export warms."""
        return [('step',)] + [('bundle', K) for K in sorted(self._bundles)]

    def pin_state(self, persist, device):
        """Commit the step's DONATED persistables to their device
        placement BEFORE the first jitted call, so the entry's argument
        signature is stable for the artifact's whole life: donated
        outputs come back COMMITTED device arrays, and a first call made
        with uncommitted arrays (fresh startup outputs, host ndarrays
        io.load wrote into the scope) would specialize the executable
        once more on call two — the PR 4 "warm twice" run_bundle wart.
        One donation layout, one compile per signature; steady state is
        a per-name attribute check.

        Only the donation set is touched: read-only persistables are
        never re-emitted by the step, so their committedness can never
        flip between calls — and re-placing them would needlessly break
        buffer identity for frozen weights callers still hold.

        Mutates `persist` in place; returns the names re-placed (the
        caller syncs those back into the scope so the pinned arrays ARE
        the scope arrays). `device=None` (mesh-placed programs, executors
        without a place) is a no-op — those paths own their placement."""
        if device is None:
            return []
        from jax.sharding import NamedSharding
        pinned = []
        for n in self.donate_names:
            v = persist.get(n)
            if v is None or isinstance(v, SeqValue):
                continue
            if isinstance(v, jax.Array):
                if (getattr(v, 'committed', True)
                        or isinstance(v.sharding, NamedSharding)
                        or len(v.sharding.device_set) > 1):
                    continue
                persist[n] = jax.device_put(v, device)
            else:
                persist[n] = jax.device_put(np.asarray(v), device)
            pinned.append(n)
        return pinned

    def touched_rows(self, feed):
        """HOST-side touched-row derivation for one fed batch: which
        rows of each sparse-plan table will the step's sparse update
        actually write? The answer is already in the feed — every
        eligible table's lookup ids are feed/persist vars
        (_sparse_embedding_plan resolves them before the forward runs),
        so the streaming delta publisher (paddle_tpu.streaming) reads
        the touched set without fetching anything from the device or
        changing the compiled step.

        Returns {table name: sorted unique int64 row ids} for tables on
        the sparse path whose ids are present in `feed` (padding_idx
        rows excluded — the lookup rule zeroes their gradient). Tables
        training DENSE (no sparse plan) are absent: their update writes
        every row, and a row-delta push would under-report; the
        publisher warns on that case."""
        out = {}
        for w, plan in self.sparse_plan.items():
            parts = []
            ok = True
            for _op_idx, ids_name, pad in plan['lookups']:
                v = feed.get(ids_name)
                if v is None:
                    ok = False
                    break
                ids = np.asarray(lowering.data_of(v)).reshape(-1)
                if pad is not None and pad >= 0:
                    ids = ids[ids != pad]
                parts.append(ids.astype(np.int64))
            if ok and parts:
                out[w] = np.unique(np.concatenate(parts))
        return out

    @property
    def state_names(self):
        """The persistable names this step reads/writes — the artifact's
        state_dict seam (what sharded checkpointing walks)."""
        return list(self.persist_in)

    def state_dict(self, scope):
        """Placement-true {name: jax.Array} view of THIS step's
        persistable state, read live from `scope` — the state_dict seam
        owned by the artifact rather than bolted onto the executor: a
        mesh-placed array keeps its NamedSharding (save_sharded then
        writes only addressable shards). LoD (SeqValue) state is skipped,
        matching Executor.state_dict."""
        out = {}
        for n in self.persist_in:
            v = scope._chain_get(n)
            if v is None or isinstance(v, SeqValue):
                continue
            out[n] = v if isinstance(v, jax.Array) else jnp.asarray(v)
        return out


# ---------------------------------------------------------------------------
# AOT warm signatures (docs/perf.md#aot): serialize the compiled-signature
# set of a WARMED executor so a cold replica / elastic restart reaches its
# first step (first token) with ZERO online compiles. The executable bytes
# are the persistent XLA compilation cache's (PADDLE_TPU_COMPILE_CACHE) —
# this packages them WITH a typed manifest of every warm signature (feed
# names/shapes/dtypes, fetches, donation plan, program fingerprint), so the
# blob travels across machines and `tools/program_lint.py --aot` can detect
# a stale blob statically instead of a silent online recompile.
# ---------------------------------------------------------------------------

AOT_MANIFEST = 'aot_manifest.json'
AOT_CACHE_DIR = 'xla_cache'
AOT_FORMAT = 'paddle_tpu-aot-v1'


def program_fingerprint(program):
    """Process-independent structural identity of a Program: sha256 over
    its canonical dict serialization (the save_inference_model shape, so
    a saved artifact round-trips to the same fingerprint)."""
    import hashlib
    import json
    doc = json.dumps(program._to_dict(), sort_keys=True, default=str)
    return hashlib.sha256(doc.encode('utf-8')).hexdigest()[:16]


def stable_signature(art):
    """Process-independent identity of one compiled step signature —
    unlike the Executor's in-process cache key (which embeds the
    program's per-process _uid), this survives restarts and travels with
    an AOT export: program fingerprint + feed signature + fetch set +
    persistable set + the mode flags that change the lowering. Cached on
    the artifact."""
    if art._stable_sig is not None:
        return art._stable_sig
    import hashlib
    import json
    src = art._source_program if art._source_program is not None \
        else art.program
    payload = json.dumps({
        'program': program_fingerprint(src),
        'feed_sig': [[str(x) for x in sig] for sig in (art._feed_sig or ())],
        'fetches': list(art.fetch_names),
        'persist_in': list(art.persist_in),
        'donates': sorted(art.donate_names),
        'amp': bool(art.amp),
        'guard': bool(art.guard),
        'remat': bool(art.use_remat),
        'mesh': (sorted([str(a), int(s)] for a, s in art.mesh.shape.items())
                 if art.mesh is not None else None),
    }, sort_keys=True)
    art._stable_sig = hashlib.sha256(
        payload.encode('utf-8')).hexdigest()[:16]
    return art._stable_sig


def _feed_entries(art):
    """Manifest feed records from the artifact's placed-feed signature:
    [{'name', 'shape', 'dtype', 'seq'}...] (seq inputs record their dense
    data plane's shape)."""
    out = []
    for sig in (art._feed_sig or ()):
        if len(sig) == 4 and sig[1] == 'seq':
            name, _, shape, dtype = sig
            seq = True
        else:
            name, shape, dtype = sig
            seq = False
        out.append({'name': name, 'shape': [int(d) for d in shape],
                    'dtype': str(dtype), 'seq': seq})
    return out


def aot_manifest(executor):
    """The typed signature-set manifest of a warmed executor's compiled
    artifacts (one entry per cache entry): what write_aot serializes and
    program_lint --aot checks against."""
    sigs = []
    for art in executor._cache.values():
        src = art._source_program if art._source_program is not None \
            else art.program
        sigs.append({
            'sig': stable_signature(art),
            'key': art._key_id,
            'program': program_fingerprint(src),
            'feeds': _feed_entries(art),
            'fetches': list(art.fetch_names),
            'donates': sorted(art.donate_names),
            'readonly': sorted(art.readonly_names),
            'bundles': sorted(art._bundles),
            # which entry points were actually first-called here — a
            # replica warmed only through run_bundle never serialized
            # the plain step, and the importer's stale detection must
            # know that (Executor._aot_warmed)
            'warmed_step': bool(getattr(art, '_obs_compiled', False)),
            'guard': bool(art.guard),
            'amp': bool(art.amp),
            'mesh': (sorted([str(a), int(s)]
                            for a, s in art.mesh.shape.items())
                     if art.mesh is not None else None),
        })
    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = None
    return {'format': AOT_FORMAT, 'jax': jax.__version__,
            'platform': platform, 'signatures': sigs}


def write_aot(dirname, executor):
    """Export the executor's warm signature set: the manifest plus the
    persistent-compile-cache entries (the serialized XLA executables)
    under `dirname/xla_cache/`. Requires the executor to have been
    constructed with PADDLE_TPU_COMPILE_CACHE wired — the on-disk
    executable IS the AOT payload; without it there is nothing
    transportable to export. Returns (manifest_path, manifest)."""
    import json
    import shutil
    src = executor._compile_cache_dir
    if not src or not os.path.isdir(src):
        raise RuntimeError(
            'export_warm_signatures needs the persistent compilation '
            'cache: construct the Executor with PADDLE_TPU_COMPILE_CACHE='
            '<dir> set, warm the signature set, then export — the cached '
            'XLA executables are the AOT payload (docs/perf.md#aot)')
    man = aot_manifest(executor)
    if not man['signatures']:
        raise RuntimeError(
            'export_warm_signatures: this executor has compiled nothing '
            'yet — warm the signature set (run / run_bundle / serving '
            'warmup) before exporting')
    os.makedirs(dirname, exist_ok=True)
    cache_dst = os.path.join(dirname, AOT_CACHE_DIR)
    os.makedirs(cache_dst, exist_ok=True)
    # ship only the entries THIS executor's first calls wrote when that
    # tracked set is authoritative (every first call cold-compiled here:
    # no persistent hits served entries the tracker never saw). A warm
    # process exporting a shared long-lived cache dir falls back to the
    # whole dir — over-shipping beats a blob whose signatures miss.
    tracked = getattr(executor, '_warm_entries', None) or set()
    use_tracked = bool(tracked) and executor._persistent_hits == 0
    scope = 'tracked' if use_tracked else 'full_dir'
    copied = []
    with os.scandir(src) as it:
        for e in it:
            if not e.is_file() or e.name.endswith('-atime'):
                continue
            if use_tracked and e.name not in tracked:
                continue
            shutil.copy2(e.path, os.path.join(cache_dst, e.name))
            copied.append(e.name)
    man['cache_entries'] = sorted(copied)
    man['cache_scope'] = scope
    path = os.path.join(dirname, AOT_MANIFEST)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(man, f, indent=1)
    os.replace(tmp, path)
    return path, man


def read_aot(dirname):
    """Load (and format-check) an AOT manifest from an export dir (or a
    manifest file path). Raises RuntimeError on a missing/alien blob."""
    import json
    path = dirname
    if os.path.isdir(path):
        path = os.path.join(path, AOT_MANIFEST)
    if not os.path.exists(path):
        raise RuntimeError('no AOT manifest at %r (expected %s)'
                           % (dirname, AOT_MANIFEST))
    with open(path) as f:
        man = json.load(f)
    if man.get('format') != AOT_FORMAT:
        raise RuntimeError('AOT manifest %r has format %r, expected %r'
                           % (path, man.get('format'), AOT_FORMAT))
    return man


def aot_check(src, program):
    """Static staleness check of an exported AOT blob against a program
    artifact (tools/program_lint.py --aot): does any exported signature
    actually match THIS program, do the recorded feed shapes/dtypes still
    exist on it, and does the recorded donation plan agree with the
    program's memory plan? Returns a list of human-readable problems —
    empty means a replica loading this blob warms without online
    compiles; any problem means a stale blob whose first calls would
    silently recompile (the exact failure this check types)."""
    manifest = src if isinstance(src, dict) else read_aot(src)
    problems = []
    fp = program_fingerprint(program)
    sigs = manifest.get('signatures', [])
    if not sigs:
        return ['AOT manifest records no signatures — nothing is warmed']
    if jax.__version__ != manifest.get('jax'):
        problems.append(
            'AOT blob was exported under jax %s but this process runs '
            '%s — serialized executables will not deserialize; every '
            'first call compiles online'
            % (manifest.get('jax'), jax.__version__))
    matching = [s for s in sigs if s.get('program') == fp]
    if not matching:
        problems.append(
            'no exported signature matches this program (fingerprint %s; '
            'exported: %s) — the blob was built from a different/older '
            'program and every first call would compile online'
            % (fp, sorted({str(s.get('program')) for s in sigs})))
    blk = program.global_block()
    from .passes import memory_plan
    plan = memory_plan(program)
    persistable = {v.name for v in program.list_vars() if v.persistable}
    for s in matching or sigs:
        tag = 'signature %s' % s.get('sig', '?')
        for f in s.get('feeds', []):
            var = blk.vars.get(f.get('name'))
            if var is None:
                problems.append(
                    '%s: feed %r is not a variable of this program'
                    % (tag, f.get('name')))
                continue
            want = str(var.dtype)
            got = str(f.get('dtype'))
            # int64-declared vars run int32 on device (x64 disabled), and
            # bf16 feeds arrive as the var's compute dtype — compare the
            # placed dtype only when the var's declared one maps to it
            if want == 'int64':
                want = 'int32'
            if got != want and want != 'bfloat16':
                problems.append(
                    '%s: feed %r recorded dtype %s but the program '
                    'declares %s' % (tag, f['name'], got, want))
            vshape = tuple(int(d) for d in var.shape)
            rec = tuple(int(d) for d in f.get('shape', ()))
            # the leading (batch) dim is -1/any in program metadata; the
            # trailing dims must agree where the program declares them
            if len(rec) == len(vshape):
                for rd, vd in zip(rec[1:], vshape[1:]):
                    if vd > 0 and rd != vd:
                        problems.append(
                            '%s: feed %r recorded shape %r but the '
                            'program declares %r'
                            % (tag, f['name'], list(rec), list(vshape)))
                        break
        for name in s.get('fetches', []):
            if name not in blk.vars and not any(
                    name in b.vars for b in program.blocks):
                problems.append(
                    '%s: fetch %r is not produced by this program'
                    % (tag, name))
        stale_don = sorted(set(s.get('donates', [])) - plan.write_set)
        if stale_don:
            problems.append(
                '%s: recorded donation of %r but this program\'s memory '
                'plan does not write them — the donation vector changed '
                'since export' % (tag, stale_don))
        missing_don = sorted(
            (plan.write_set & persistable) - set(s.get('donates', []))
            - set(s.get('readonly', [])))
        if missing_don:
            problems.append(
                '%s: the program now writes persistable(s) %r that the '
                'exported plan never donated — the compiled layout is '
                'stale' % (tag, missing_don))
    return problems


def _nan_inf_hook(i, op, dt, env):
    for slot, vs in op.outputs.items():
        for v in vs:
            val = env.get(v.name)
            if val is None:
                continue
            for leaf in jax.tree_util.tree_leaves(val):
                if (hasattr(leaf, 'dtype')
                        and jnp.issubdtype(leaf.dtype, jnp.floating)
                        and not bool(jnp.isfinite(leaf).all())):
                    raise FloatingPointError(
                        "NaN/Inf in output %r of op #%d %r" %
                        (v.name, i, op.type))
